"""Fleet-scale observability tests (ISSUE 15).

The acceptance bar: a 2-shard TCP fleet replay (REAL shard-server
subprocesses — separate tracers, separate clocks, separate flight
books) produces ONE ``fleet_trace.json`` in which every router
sub-request span parents under its router request span, every shard
frontend span joins its sub-request, every ``serving.score`` leaf joins
its shard's dispatch span, and skew-corrected timestamps are monotone
parent -> child within every trace (to the recorded clock-sync
uncertainty). Fleet ``check_conservation`` — router admitted == Σ
shard-attributed terminals + router-local outcomes — passes across a
mid-flood two-step fleet flip with one SIGKILLed shard, and an injected
dropped response makes it FAIL. An SLO burn-rate alert fires on an
induced error burst and appears both as a flight event and a registry
gauge.
"""

import json
import os
import subprocess
import sys

import pytest

from photon_ml_tpu.game.data import build_game_dataset
from photon_ml_tpu.obs.fleet import (
    FleetCollector,
    fleet_check_conservation,
    main as fleet_main,
    stitch_spans,
    verify_fleet_trace,
)
from photon_ml_tpu.obs.flight_recorder import (
    FlightRecorder,
    reset_flight_recorder,
)
from photon_ml_tpu.obs.registry import MetricsRegistry
from photon_ml_tpu.obs.slo import (
    SLOEngine,
    SLOSpec,
    default_router_slos,
    default_serving_slos,
    parse_slo_specs,
)
from photon_ml_tpu.obs.trace import (
    Tracer,
    export_chrome_trace,
    reset_tracer,
    tracer,
    tracing_scope,
)
from photon_ml_tpu.serving import (
    MicroBatcher,
    RoutingPolicy,
    ServingFrontend,
    ServingMetrics,
    ServingModel,
    ServingPrograms,
    ShardRouter,
)
from tests.test_obs import _Client
from tests.test_serving import SHARDS, make_bank, synth_model, synth_records
from tests.test_shard_routing import synthetic_records

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IDS = sorted(f"user{i:02d}" for i in range(14))

# One synthetic shard-server subprocess: its OWN tracer epoch, flight
# recorder and wall clock — what makes the stitching/skew machinery
# testable for real. Banks are seeded, so every shard of a generation
# agrees bitwise with every other process that builds it.
SHARD_SCRIPT = r"""
import json, os, sys, time
import numpy as np
from photon_ml_tpu.game.config import FeatureShardConfiguration
from photon_ml_tpu.serving import (
    ServingModel, ServingPrograms, ShardServer, bank_from_arrays,
)
from photon_ml_tpu.utils.index_map import IndexMap

shard, count = int(sys.argv[1]), int(sys.argv[2])
E, d_g, d_u = 14, 6, 4
ids = sorted(f"user{i:02d}" for i in range(E))
SHARDS = [
    FeatureShardConfiguration("g", ["features"]),
    FeatureShardConfiguration("u", ["userFeatures"]),
]
imaps = {
    "g": IndexMap({f"g{j}\t": j for j in range(d_g)}),
    "u": IndexMap({f"u{j}\t": j for j in range(d_u)}),
}

def build(gen):
    rng = np.random.default_rng(1234 + gen)
    fe = rng.standard_normal(d_g).astype(np.float32)
    re = rng.standard_normal((E, d_u)).astype(np.float32)
    return bank_from_arrays(
        fixed=[("global", "g", fe)],
        random=[("per-user", "userId", "u", re, ids)],
        shard_widths={"g": 4, "u": 4},
        index_maps=imaps,
        entity_shard=(shard, count),
    )

sm = ServingModel(
    build(1), ServingPrograms((1, 8)), partial=True,
    entity_shard=(shard, count),
)

def stager(obj):
    return sm.prepare_swap_bank(build(2))

srv = ShardServer(
    sm, SHARDS, (shard, count), stager=stager, has_response=False,
).start()
print(json.dumps({"port": srv.port, "pid": os.getpid()}), flush=True)
while True:
    time.sleep(0.1)
"""


@pytest.fixture(scope="module")
def shard_fleet():
    """Two real shard-server subprocesses (tracing ON) + their ports."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PHOTON_TRACE": "1"}
    procs = []
    try:
        for s in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", SHARD_SCRIPT, str(s), "2"],
                cwd=REPO, env=env, stdout=subprocess.PIPE, text=True,
            ))
        meta = []
        for p in procs:
            line = p.stdout.readline()
            assert line, "shard subprocess died before binding"
            meta.append(json.loads(line))
        yield procs, meta
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)


# -- the {"op": "trace"} cursor contract over a live socket -------------------


@pytest.fixture
def traced_frontend(rng):
    recs = synth_records(rng)
    ds = build_game_dataset(recs, SHARDS, ["userId"])
    bank = make_bank(synth_model(rng), ds)
    sm = ServingModel(bank, ServingPrograms((1, 8)))
    metrics = ServingMetrics()
    batcher = MicroBatcher(sm.current, sm.programs, metrics)
    fe = ServingFrontend(batcher, sm, SHARDS, metrics=metrics,
                         port=0).start()
    with tracing_scope(True):
        tracer().clear()
        yield recs, fe
    fe.stop_accepting()
    batcher.drain(10.0)
    fe.close()
    batcher.close()


class TestTraceOp:
    def test_cursor_polls_never_duplicate_or_drop(self, traced_frontend):
        recs, fe = traced_frontend
        c = _Client(fe.port)
        try:
            for r in recs[:5]:
                assert c.ask(r)["status"] == "ok"
            r1 = c.ask({"op": "trace", "cursor": 0, "uid": "t1"})
            assert r1["status"] == "ok" and r1["uid"] == "t1"
            assert r1["dropped"] == 0
            assert r1["enabled"] is True
            assert r1["pid"] == os.getpid()
            for key in ("epoch_wall", "epoch_perf", "now_perf",
                        "max_spans"):
                assert key in r1, key
            seqs = [s["seq"] for s in r1["spans"]]
            assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
            assert r1["cursor"] == seqs[-1]
            names = {s["name"] for s in r1["spans"]}
            assert "frontend.request" in names
            assert "serving.dispatch" in names
            # an immediate re-poll at the cursor returns NOTHING — no
            # span is ever sent twice
            r2 = c.ask({"op": "trace", "cursor": r1["cursor"]})
            assert r2["spans"] == [] and r2["cursor"] == r1["cursor"]
            # more traffic -> only the NEW spans
            for r in recs[5:8]:
                assert c.ask(r)["status"] == "ok"
            r3 = c.ask({"op": "trace", "cursor": r2["cursor"]})
            new_seqs = [s["seq"] for s in r3["spans"]]
            assert new_seqs and min(new_seqs) > r1["cursor"]
            # union across polls covers every span the tracer retains
            assert (
                {s["seq"] for s in r1["spans"]} | set(new_seqs)
                == {s.seq for s in tracer().snapshot()}
            )
            # a cursor from before a ring reset restarts cleanly
            tracer().clear()
            for r in recs[8:10]:
                assert c.ask(r)["status"] == "ok"
            r4 = c.ask({"op": "trace", "cursor": r3["cursor"]})
            assert r4["spans"], "reset must replay from the beginning"
            bad = c.ask({"op": "trace", "cursor": "xyz"})
            assert bad["status"] == "error"
            assert bad["error"] == "BAD_REQUEST"
        finally:
            c.close()

    def test_evictions_between_polls_are_counted(self, traced_frontend,
                                                 monkeypatch):
        recs, fe = traced_frontend
        monkeypatch.setenv("PHOTON_TRACE_SPANS", "8")
        t = reset_tracer()
        try:
            assert t.max_spans == 8
            c = _Client(fe.port)
            try:
                for r in recs[:12]:  # >8 spans' worth of traffic
                    assert c.ask(r)["status"] == "ok"
                resp = c.ask({"op": "trace", "cursor": 0})
            finally:
                c.close()
            assert resp["max_spans"] == 8
            assert resp["dropped"] > 0, (
                "ring evictions between polls must be counted"
            )
            assert len(resp["spans"]) <= 8
        finally:
            monkeypatch.delenv("PHOTON_TRACE_SPANS")
            reset_tracer()


# -- the live collector over a REAL 2-subprocess TCP fleet --------------------


class TestFleetCollectorLive:
    def _flood(self, router, records):
        out = []
        for rec in records:
            out.append(router.score_record(rec))
        return out

    def test_fleet_trace_and_conservation_across_swap_and_kill(
        self, shard_fleet, rng, tmp_path
    ):
        from photon_ml_tpu import ownership

        procs, meta = shard_fleet
        ports = [m["port"] for m in meta]
        router_book = FlightRecorder(capacity=4096)
        with tracing_scope(True):
            tracer().clear()
            router = ShardRouter(
                [("127.0.0.1", pt) for pt in ports],
                entity_ids={"userId": IDS},
                shard_configs=SHARDS,
                policy=RoutingPolicy(subrequest_timeout_s=5.0),
                recorder=router_book,
            )
            router.connect()
            collector = FleetCollector(
                [
                    ("shard0", "127.0.0.1", ports[0]),
                    ("shard1", "127.0.0.1", ports[1]),
                ],
                local_name="router",
                connect_timeout_s=10.0,
            )
            try:
                recs = synthetic_records(rng, IDS, n=24)
                cold = self._flood(router, recs)
                assert not any(o.degraded for o in cold)
                ok1 = collector.poll_once()
                assert all(ok1.values()), ok1
                # warm pass: identical records answer from the hot
                # cache (fan-out 0 -> the "cache" attribution bucket)
                warm = self._flood(router, recs)
                assert any(o.cache_hit for o in warm)
                # -- mid-run two-step fleet flip ------------------------
                res = router.coordinate_swap("synthetic")
                assert res["ok"] and res["generation"] == 2, res
                gen2 = self._flood(router, recs)
                assert all(o.generation == 2 for o in gen2)
                collector.poll_once()
                # -- SIGKILL shard 1, flood a variant (cache-missing)
                # trace: shard 1's entities degrade, shard 0's stay
                # exact ------------------------------------------------
                procs[1].kill()
                procs[1].wait(timeout=30)
                variants = []
                for r in recs:
                    v = json.loads(json.dumps(r))
                    for bag in ("features", "userFeatures"):
                        for f in v.get(bag) or []:
                            f["value"] = float(f["value"]) * 1.25 + 0.5
                    variants.append(v)
                after = self._flood(router, variants)
                owners = {
                    r["uid"]: ownership.owner_of(
                        IDS.index(r["metadataMap"]["userId"]), 2
                    )
                    for r in recs
                }
                n_deg = 0
                for rec, o in zip(variants, after):
                    if owners[rec["uid"]] == 1:
                        assert o.degraded, rec["uid"]
                        n_deg += 1
                    else:
                        assert not o.degraded, rec["uid"]
                assert n_deg > 0
                collector.stop(final_poll=True)
                status = collector.member_status()
                # the killed shard stopped answering, but everything
                # polled BEFORE the kill stays merged
                assert status["shard1"]["errors"] >= 1
                assert status["shard1"]["spans"] > 0
                assert status["shard0"]["ring_dropped"] == 0
                for name in ("shard0", "shard1"):
                    assert (
                        status[name]["clock_offset_uncertainty_s"]
                        is not None
                    )
                # -- ONE merged fleet trace, fully verified -------------
                stitched = collector.stitched_spans()
                verdict = verify_fleet_trace(stitched)
                assert verdict["ok"], verdict["violations"]
                assert verdict["router_subrequests"] > 0
                assert verdict["frontend_requests"] > 0
                assert verdict["score_leaves"] > 0
                members = {s["member"] for s in stitched}
                assert members == {"router", "shard0", "shard1"}
                sids = [s["span_id"] for s in stitched]
                assert len(sids) == len(set(sids)), "namespaced ids collide"
                # spans from BOTH generations straddle the flip
                gens = {
                    s["attrs"].get("generation")
                    for s in stitched
                    if s["name"] == "serving.dispatch"
                }
                assert {1, 2} <= gens, gens
                out = str(tmp_path / "fleet_trace.json")
                n = collector.export(out)
                data = json.load(open(out))
                assert len(data["traceEvents"]) == n
                lanes = {
                    e["args"]["name"]: e["pid"]
                    for e in data["traceEvents"]
                    if e.get("ph") == "M"
                }
                assert len(lanes) == 3, lanes
                assert data["otherData"]["verification"]["ok"]
                for m in data["otherData"]["members"].values():
                    assert "clock_offset_s" in m
                # -- fleet conservation ACROSS the swap + the kill ------
                flight = collector.collect_flight()
                assert flight["shard0"]["complete"]
                assert not flight["shard1"]["complete"]
                books = {
                    name: {
                        "conservation": flight[name].get("conservation")
                        or {},
                        "complete": flight[name]["complete"],
                        "shard_indices": [i],
                    }
                    for i, name in enumerate(("shard0", "shard1"))
                }
                cons = fleet_check_conservation(
                    router_book.check_conservation(), books
                )
                assert cons["ok"], cons
                attr = cons["terminal_by_attribution"]
                assert attr.get("cache", 0) > 0, attr
                assert attr.get("degraded", 0) >= n_deg, attr
                assert any(k.startswith("shard:") for k in attr), attr
                assert sum(attr.values()) == cons["terminal_total"]
                assert cons["shards"]["shard0"]["join_ok"] is True
                assert cons["shards"]["shard1"]["join_ok"] is None
                # per-generation split re-sums across the flip
                assert set(cons["terminal_by_generation"]) >= {"1", "2"}
                # -- negative pin: one dropped response breaks it -------
                router_book.note_admitted()  # admitted, never terminal
                bad = fleet_check_conservation(
                    router_book.check_conservation(), books
                )
                assert not bad["ok"]
                assert not bad["router_ok"]
                # and a doctored shard book (served < attributed) fails
                # the join on a COMPLETE shard
                doctored = json.loads(json.dumps(books))
                doctored["shard0"]["conservation"]["terminal"]["ok"] = 0
                bad2 = fleet_check_conservation(
                    {**router_book.check_conservation(),
                     "admitted": router_book.check_conservation()[
                         "admitted"] - 1},
                    doctored,
                )
                assert not bad2["ok"]
                assert bad2["shards"]["shard0"]["join_ok"] is False
            finally:
                router.close()


class TestDriverFleetObsFinish:
    def test_finish_writes_fleet_artifacts_and_block(
        self, shard_fleet, tmp_path
    ):
        """The driver's --fleet-obs-dir finalizer: stops the collector,
        writes fleet_trace.json + fleet_conservation.json, returns the
        metrics.json block. Runs against the live shard0 subprocess
        (shard1 may already be dead — an unreachable member must be
        reported, never crash the finalizer)."""
        from photon_ml_tpu.cli.serving_driver import (
            ServingDriver,
            ServingParams,
        )

        procs, meta = shard_fleet
        port0 = meta[0]["port"]
        assert procs[0].poll() is None, "shard0 must be alive"
        fo = tmp_path / "fleet-obs"
        fo.mkdir()
        d = ServingDriver.__new__(ServingDriver)
        d.params = ServingParams(
            shard_servers=f"127.0.0.1:{port0}",
            fleet_obs_dir=str(fo),
        )
        d.logger = type(
            "L", (), {"info": lambda self, *a, **k: None}
        )()
        d.fleet_collector = FleetCollector(
            [("shard0", "127.0.0.1", port0)],
            local_name="router",
            connect_timeout_s=10.0,
        )
        d.fleet_collector.poll_once()
        block = d._finish_fleet_obs()
        assert block is not None
        assert os.path.exists(block["fleet_trace_path"])
        assert os.path.exists(str(fo / "fleet_conservation.json"))
        assert set(block["members"]) == {"router", "shard0"}
        assert "conservation" in block
        data = json.load(open(block["fleet_trace_path"]))
        assert "verification" in data["otherData"]
        # a driver without the flag no-ops
        d2 = ServingDriver.__new__(ServingDriver)
        d2.fleet_collector = None
        assert d2._finish_fleet_obs() is None


# -- stitching / skew units (deterministic) -----------------------------------


def _mk_payload(name, spans, *, offset=0.0, unc=0.001, pid=100):
    return {
        "name": name,
        "pid": pid,
        "spans": spans,
        "epoch_wall": 0.0,
        "epoch_perf": 0.0,
        "offset_s": offset,
        "offset_unc_s": unc,
        "wall_mapped": False,
    }


def _span(name, sid, t0, t1, *, trace="tr1", parent=None, attrs=None):
    return {
        "name": name, "trace_id": trace, "span_id": sid,
        "parent_id": parent, "t0": t0, "t1": t1, "tid": 1, "seq": 1,
        "attrs": dict(attrs or {}),
    }


class TestStitching:
    def test_skew_correction_restores_parent_child_monotonicity(self):
        """A shard whose clock runs 50ms BEHIND emits child spans that
        LOOK earlier than their router parent; the measured offset must
        undo exactly that."""
        skew = 0.050
        router = [_span("router.request", "r1", 10.000, 10.010),
                  _span("router.subrequest", "s1", 10.001, 10.009,
                        parent="r1")]
        # the shard's clock reads t - skew at true time t: a span that
        # truly started at 10.002 is stamped 9.952 — before its parent
        shard = [_span("frontend.request", "f1", 10.002 - skew,
                       10.008 - skew, parent="s1")]
        stitched = stitch_spans([
            _mk_payload("router", router, offset=0.0, unc=0.0),
            _mk_payload("shard0", shard, offset=-skew, unc=0.0005,
                        pid=200),
        ])
        v = verify_fleet_trace(stitched)
        assert v["ok"], v["violations"]
        f1 = next(s for s in stitched if s["span_id"] == "shard0:f1")
        assert abs(f1["t0"] - 10.002) < 1e-9
        assert f1["parent_id"] == "router:s1"
        # WITHOUT the correction the nesting check fails loudly
        broken = stitch_spans([
            _mk_payload("router", router, offset=0.0, unc=0.0),
            _mk_payload("shard0", shard, offset=0.0, unc=0.0005,
                        pid=200),
        ])
        v2 = verify_fleet_trace(broken)
        assert not v2["ok"]
        assert any("before its parent" in x for x in v2["violations"])

    def test_dispatch_leaves_expand_and_join_their_member(self):
        shard = [
            _span("frontend.request", "f1", 1.0, 1.4, parent="s1"),
            _span("serving.dispatch", "d1", 1.1, 1.3, trace="td",
                  attrs={"generation": 1, "shape": 8,
                         "traces": [["tr1", "f1", False]]}),
        ]
        router = [_span("router.request", "r1", 0.9, 1.5),
                  _span("router.subrequest", "s1", 0.95, 1.45,
                        parent="r1")]
        stitched = stitch_spans([
            _mk_payload("router", router, unc=0.0),
            _mk_payload("shard0", shard, unc=0.0, pid=2),
        ])
        leaves = [s for s in stitched if s["name"] == "serving.score"]
        assert len(leaves) == 1
        leaf = leaves[0]
        assert leaf["member"] == "shard0"
        assert leaf["parent_id"] == "shard0:f1"
        assert leaf["attrs"]["dispatch_span"] == "shard0:d1"
        v = verify_fleet_trace(stitched)
        assert v["ok"], v["violations"]
        # a leaf whose dispatch span vanished is a named violation
        gone = [s for s in stitched if s["name"] != "serving.dispatch"]
        v2 = verify_fleet_trace(gone)
        assert not v2["ok"]
        assert any("dispatch_span" in x for x in v2["violations"])


# -- SLO engine ---------------------------------------------------------------


class TestSLOEngine:
    def _avail_spec(self, **kw):
        base = dict(
            name="avail", objective=0.9, kind="availability",
            metric="req_total", bad_metric="req_bad",
            short_window_s=10.0, long_window_s=60.0, burn_threshold=2.0,
        )
        base.update(kw)
        return SLOSpec(**base).validate()

    def test_burst_fires_alert_as_flight_event_and_gauge(self):
        reg = MetricsRegistry()
        total = reg.counter("req_total")
        bad = reg.counter("req_bad")
        rec = FlightRecorder(capacity=64)
        eng = SLOEngine(reg, [self._avail_spec()], recorder=rec)
        # healthy baseline: 1% errors against a 10% budget
        t = 0.0
        for _ in range(70):
            total.inc(100)
            bad.inc(1)
            eng.tick(t)
            t += 1.0
        assert not eng.alert_active("avail")
        assert reg.gauge("slo_alert").value(slo="avail") == 0.0
        # induced error burst: 80% errors = burn 8 >> threshold 2;
        # the long window dilutes slower, so keep burning past it
        fired_at = None
        for i in range(60):
            total.inc(100)
            bad.inc(80)
            eng.tick(t)
            t += 1.0
            if eng.alert_active("avail"):
                fired_at = i
                break
        assert fired_at is not None, "burst never fired the alert"
        # the alert is BOTH a flight event and a live gauge
        kinds = [e["kind"] for e in rec.events()]
        assert "slo.alert" in kinds
        fields = next(
            e for e in rec.events() if e["kind"] == "slo.alert"
        )["fields"]
        assert fields["slo"] == "avail"
        assert fields["burn_short"] > 2.0
        assert reg.gauge("slo_alert").value(slo="avail") == 1.0
        assert (
            reg.gauge("slo_burn_rate").value(slo="avail", window="short")
            > 2.0
        )
        # recovery: the SHORT window resets fast -> alert clears (the
        # multi-window AND), with a clear event on the ring
        for _ in range(30):
            total.inc(100)
            eng.tick(t)
            t += 1.0
            if not eng.alert_active("avail"):
                break
        assert not eng.alert_active("avail")
        assert "slo.clear" in [e["kind"] for e in rec.events()]
        assert reg.gauge("slo_alert").value(slo="avail") == 0.0
        st = eng.status()
        assert st["alerts_fired"] == 1
        assert st["alerts_active"] == []

    def test_short_blip_does_not_page(self):
        """One transient spike trips the short window but never the
        long one — the multi-window AND holds the page."""
        reg = MetricsRegistry()
        total = reg.counter("req_total")
        bad = reg.counter("req_bad")
        eng = SLOEngine(reg, [self._avail_spec()], recorder=None)
        t = 0.0
        for _ in range(70):
            total.inc(100)
            eng.tick(t)
            t += 1.0
        # a 3-tick blip: short burn explodes, long stays dilute
        for _ in range(3):
            total.inc(100)
            bad.inc(80)
            eng.tick(t)
            t += 1.0
        assert (
            reg.gauge("slo_burn_rate").value(slo="avail", window="short")
            > 2.0
        )
        assert not eng.alert_active("avail")

    def test_latency_slo_over_registry_histogram(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_s", bounds=(0.01, 0.1, 1.0))
        spec = SLOSpec(
            name="lat", objective=0.9, kind="latency", metric="lat_s",
            latency_threshold_s=0.1, short_window_s=10.0,
            long_window_s=60.0, burn_threshold=2.0,
        ).validate()
        eng = SLOEngine(reg, [spec])
        t = 0.0
        for _ in range(70):
            for _ in range(9):
                h.observe(0.05)
            h.observe(0.05)
            eng.tick(t)
            t += 1.0
        assert not eng.alert_active("lat")
        for _ in range(70):
            for _ in range(5):
                h.observe(5.0)  # past the 0.1s threshold
            for _ in range(5):
                h.observe(0.05)
            eng.tick(t)
            t += 1.0
        assert eng.alert_active("lat")
        # a threshold that is not a bucket bound is a named refusal
        bad_spec = SLOSpec(
            name="lat2", objective=0.9, kind="latency", metric="lat_s",
            latency_threshold_s=0.07,
        ).validate()
        eng2 = SLOEngine(reg, [bad_spec])
        with pytest.raises(ValueError, match="not a bucket bound"):
            eng2.tick(0.0)

    def test_spec_parsing(self):
        specs = parse_slo_specs(
            '[{"name": "a", "objective": 0.99, '
            '"metric": "t", "bad_metric": "b"}]'
        )
        assert specs[0].name == "a" and specs[0].kind == "availability"
        assert parse_slo_specs("default") == default_serving_slos()
        assert default_router_slos()[0].metric == "router_requests_total"
        with pytest.raises(ValueError, match="unknown SLO spec key"):
            parse_slo_specs('{"name": "a", "objective": 0.9, '
                            '"metric": "t", "bad_metric": "b", '
                            '"shortwindow": 5}')
        with pytest.raises(ValueError, match="objective"):
            parse_slo_specs('{"name": "a", "objective": 1.5, '
                            '"metric": "t", "bad_metric": "b"}')
        with pytest.raises(ValueError):
            parse_slo_specs("")

    def test_watcher_burn_gate_replaces_raw_fraction(self):
        """The serving watcher's post-swap judgment consumes burn-rate
        state when a gate is wired: raw 100% degraded traffic does NOT
        trigger while the gate is quiet, and does the moment it burns."""
        from photon_ml_tpu.registry.watcher import RegistryWatcher

        class _Reg:
            root = "/dev/null"

        gate_state = {"burning": False}
        w = RegistryWatcher.__new__(RegistryWatcher)
        # minimal wiring: no thread, no registry IO — observe_outcome
        # only touches the window, the flags and the gate
        from photon_ml_tpu.registry.watcher import (
            HealthWindow,
            RollbackPolicy,
        )
        import threading

        w.policy = RollbackPolicy(window=8, min_requests=4,
                                  max_unhealthy_rate=0.5)
        w.burn_gate = lambda: gate_state["burning"]
        w._lock = threading.Lock()
        w._wake = threading.Event()
        w._window = HealthWindow(8)
        w._watching_swap = True
        w._rollback_wanted = False
        for _ in range(6):
            w.observe_outcome(degraded=True)
        assert not w._rollback_wanted, (
            "raw error fraction must not trigger when a burn gate is "
            "wired"
        )
        gate_state["burning"] = True
        w.observe_outcome(degraded=True)
        assert w._rollback_wanted
        assert w._wake.is_set()


# -- ring bounds from the environment (satellite) ------------------------------


class TestRingEnvBounds:
    def test_trace_ring_env_and_bounds_in_export(self, monkeypatch,
                                                 tmp_path):
        monkeypatch.setenv("PHOTON_TRACE_SPANS", "16")
        t = reset_tracer()
        try:
            assert t.max_spans == 16
            with tracing_scope(True):
                for i in range(40):
                    t.start(f"s{i}").end()
            assert len(t) == 16 and t.dropped == 24
            path = str(tmp_path / "trace.json")
            export_chrome_trace(path, t.snapshot())
            other = json.load(open(path))["otherData"]
            assert other["max_spans"] == 16
            assert other["dropped_spans"] == 24
            assert "epoch_wall" in other and "epoch_perf" in other
        finally:
            monkeypatch.delenv("PHOTON_TRACE_SPANS")
            reset_tracer()
        # garbage env falls back to the default
        monkeypatch.setenv("PHOTON_TRACE_SPANS", "banana")
        try:
            assert reset_tracer().max_spans == Tracer(1 << 16).max_spans
        finally:
            monkeypatch.delenv("PHOTON_TRACE_SPANS")
            reset_tracer()

    def test_flight_ring_env_and_bounds_in_dump(self, monkeypatch,
                                                tmp_path):
        monkeypatch.setenv("PHOTON_FLIGHT_EVENTS", "8")
        try:
            rec = reset_flight_recorder()
            assert rec.capacity == 8
            for i in range(20):
                rec.record("request.shed", i=i)
            path = str(tmp_path / "flight.json")
            rec.dump(path)
            dump = json.load(open(path))
            assert dump["capacity"] == 8
            assert dump["retained"] == 8
            assert dump["dropped"] == 12
        finally:
            monkeypatch.delenv("PHOTON_FLIGHT_EVENTS")
            reset_flight_recorder()


# -- post-hoc merge CLI --------------------------------------------------------


class TestPostHocMerge:
    def _write_dirs(self, tmp_path):
        """Two fake per-process obs dirs whose dumps nest across the
        process boundary, plus flight books (the shard's a clean drain,
        the router's an exit dump)."""
        router_dir = tmp_path / "router-obs"
        shard_dir = tmp_path / "shard0-obs"
        router_dir.mkdir()
        shard_dir.mkdir()
        rt = Tracer(64)
        root = rt.start("router.request", attrs={"uid": "q1"})
        sub = rt.start(
            "router.subrequest", trace_id=root.trace_id,
            parent_id=root.span_id, attrs={"shard": 0},
        )
        st = Tracer(64)
        f = st.start(
            "frontend.request", trace_id=root.trace_id,
            parent_id=sub.span_id,
        )
        d = st.record(
            "serving.dispatch", f.t0, f.t0 + 0.001,
            attrs={"generation": 1, "shape": 1,
                   "traces": [(root.trace_id, f.span_id, False)]},
        )
        f.end()
        sub.end()
        root.end()
        assert d.t1 is not None
        export_chrome_trace(str(router_dir / "trace.json"),
                            rt.snapshot())
        export_chrome_trace(str(shard_dir / "trace.json"), st.snapshot())
        router_rec = FlightRecorder(64)
        router_rec.note_admitted()
        router_rec.note_terminal("ok", generation=1,
                                 attribution="shard:0")
        router_rec.record("swap.fleet_commit", generation=1)
        router_rec.dump(str(router_dir / "flight.json"), reason="exit")
        shard_rec = FlightRecorder(64)
        shard_rec.note_admitted()
        shard_rec.note_terminal("ok", generation=1)
        shard_rec.record("swap.commit", generation=1)
        shard_rec.dump(str(shard_dir / "flight.json"), reason="drain")
        return router_dir, shard_dir

    def test_cli_merges_and_verifies(self, tmp_path, capsys):
        router_dir, shard_dir = self._write_dirs(tmp_path)
        out = tmp_path / "merged"
        rc = fleet_main([str(router_dir), str(shard_dir), "-o",
                         str(out)])
        assert rc == 0, capsys.readouterr().out
        data = json.load(open(out / "fleet_trace.json"))
        ver = data["otherData"]["verification"]
        assert ver["ok"], ver["violations"]
        assert ver["score_leaves"] == 1
        # flight events ride the merged timeline as instants
        instants = [e for e in data["traceEvents"] if e.get("ph") == "i"]
        assert {e["name"] for e in instants} >= {
            "swap.fleet_commit", "swap.commit",
        }
        cons = json.load(open(out / "fleet_conservation.json"))
        assert cons["ok"], cons
        assert cons["terminal_by_attribution"] == {"shard:0": 1}

    def test_cli_fails_on_broken_books(self, tmp_path, capsys):
        router_dir, shard_dir = self._write_dirs(tmp_path)
        # a dropped response: admitted with no terminal, router-side
        flight = json.load(open(router_dir / "flight.json"))
        flight["conservation"]["admitted"] += 1
        flight["conservation"]["ok"] = False
        json.dump(flight, open(router_dir / "flight.json", "w"))
        out = tmp_path / "merged"
        rc = fleet_main([
            str(router_dir), str(shard_dir),
            "--router", "router-obs", "-o", str(out),
        ])
        assert rc == 1
        cons = json.load(open(out / "fleet_conservation.json"))
        assert not cons["ok"]


# -- driver flag validation ----------------------------------------------------


class TestDriverValidation:
    def test_fleet_obs_dir_requires_router_mode(self, tmp_path):
        from photon_ml_tpu.cli.serving_driver import ServingParams

        p = ServingParams(
            game_model_input_dir="m", output_dir=str(tmp_path),
            request_paths=["x"], feature_shards=SHARDS,
            fleet_obs_dir=str(tmp_path / "fo"),
        )
        with pytest.raises(ValueError, match="router mode"):
            p.validate()

    def test_bad_slo_spec_rejected_at_parse_time(self, tmp_path):
        from photon_ml_tpu.cli.serving_driver import ServingParams

        p = ServingParams(
            game_model_input_dir="m", output_dir=str(tmp_path),
            request_paths=["x"], feature_shards=SHARDS,
            slo="{not json",
        )
        with pytest.raises((ValueError, json.JSONDecodeError)):
            p.validate()

    def test_slo_default_parses(self, tmp_path):
        from photon_ml_tpu.cli.serving_driver import ServingParams

        p = ServingParams(
            game_model_input_dir="m", output_dir=str(tmp_path),
            request_paths=["x"], feature_shards=SHARDS, slo="default",
        )
        # slo validates; the rest of this param set is fine too
        p.validate()
