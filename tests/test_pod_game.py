"""Pod-scale GAME (game/pod.py): entity-sharded random-effect banks,
two-hop all_to_all residual routing, cross-replica sharded updates.

Weak-scaling contract pinned here:
- sharded CD == replicated CD (objective and coefficients inside the
  established fp32 envelopes) at 1/2/4/8 virtual devices;
- ZERO host gathers on the routed update/score path (counted via the
  overlap.device_get seam), one batched readback per CD iteration;
- per-device bank + optimizer-state bytes at N shards <= ~1/N of the
  replicated bank (plus hash-padding slack) — the memory story that
  makes "hundreds of billions of coefficients" (PAPER.md) a mesh-size
  property instead of a host-size property;
- streaming x sharded composes end-to-end through the training driver.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.game.config import (
    ProjectorType,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.game.coordinate import (
    FixedEffectCoordinate,
    PodRandomEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import CoordinateDescent
from photon_ml_tpu.game.data import EntityIndex, GameDataset, ShardData
from photon_ml_tpu.game.pod import (
    EntityShardSpec,
    PodRandomEffectProblem,
    ShardedREBank,
    per_device_bytes,
)
from photon_ml_tpu.game.random_effect import (
    RandomEffectOptimizationProblem,
    score_random_effect,
)
from photon_ml_tpu.game.random_effect_data import build_random_effect_dataset
from photon_ml_tpu.game.residual_routing import PodResidualRouter
from photon_ml_tpu.ops.losses import LOGISTIC, loss_for_task
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
)
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.parallel.mesh import entity_mesh
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.utils.index_map import IndexMap, feature_key

sys.path.insert(0, os.path.dirname(__file__))


# ---------------------------------------------------------------------------
# synthetic data
# ---------------------------------------------------------------------------


def _synthetic_re(seed=0, n=257, E=37, d=12, k=4):
    """GameDataset + IDENTITY-projected RandomEffectDataset with weight-0
    rows, multiple capacity classes and an uneven entity histogram."""
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, E, size=n).astype(np.int32)
    ix = rng.integers(0, d, size=(n, k)).astype(np.int32)
    v = rng.normal(size=(n, k)).astype(np.float32)
    lab = (rng.uniform(size=n) > 0.5).astype(np.float32)
    w = np.ones(n, np.float32)
    w[::17] = 0.0
    off = (rng.normal(size=n) * 0.1).astype(np.float32)
    imap = IndexMap.build(
        (feature_key(f"f{i}", "") for i in range(d)), add_intercept=False
    )
    ds = GameDataset(
        uids=[str(i) for i in range(n)],
        labels=lab, offsets=off, weights=w,
        shards={
            "s": ShardData(
                indices=ix, values=v, index_map=imap, intercept_index=None
            )
        },
        entity_codes={"user": codes},
        entity_indexes={
            "user": EntityIndex.build(
                "user", [f"e{i:03d}" for i in range(E)]
            )
        },
        num_real_rows=n,
    )
    red = build_random_effect_dataset(
        ds,
        RandomEffectDataConfiguration(
            random_effect_type="user", feature_shard_id="s",
            projector_type=ProjectorType.IDENTITY,
        ),
    )
    return ds, red


def _problem(**kw):
    from photon_ml_tpu.optim.config import RegularizationType

    kw.setdefault("reg_weight", 0.5)
    return RandomEffectOptimizationProblem(
        LOGISTIC, OptimizerConfig(max_iter=5),
        RegularizationContext(RegularizationType.L2), **kw
    )


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------


class TestPodResidualRouter:
    @pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
    def test_route_in_out_round_trip(self, n_dev, rng):
        """route_out(route_in(x)) == x on every owned row: the two hops
        are exact inverses on the block layout."""
        mesh = entity_mesh(n_dev)
        codes = rng.integers(-1, 23, size=130).astype(np.int64)
        router = PodResidualRouter(mesh, codes)
        vals = rng.normal(size=130).astype(np.float32)
        slots = router.route_in(jnp.asarray(vals))
        back = np.asarray(router.route_out(slots))[:130]
        np.testing.assert_array_equal(
            back[codes >= 0], vals[codes >= 0]
        )
        assert (back[codes < 0] == 0).all()

    def test_slots_land_on_hash_owner(self, rng):
        """Every routed value sits in the slot table of the device its
        entity hashes to (code % n_dev)."""
        mesh = entity_mesh(4)
        codes = rng.integers(0, 17, size=64).astype(np.int64)
        router = PodResidualRouter(mesh, codes)
        for owner in range(4):
            gids = router.slot_row[owner]
            owned = gids[gids >= 0]
            assert (codes[owned] % 4 == owner).all()
        # each row appears exactly once across the owner tables
        all_gids = router.slot_row[router.slot_row >= 0]
        assert sorted(all_gids.tolist()) == list(range(64))

    def test_zero_host_readbacks(self, rng):
        mesh = entity_mesh(4)
        codes = rng.integers(0, 11, size=40).astype(np.int64)
        router = PodResidualRouter(mesh, codes)
        vals = jnp.asarray(rng.normal(size=40).astype(np.float32))
        overlap.reset_readback_stats()
        out = router.route_out(router.route_in(vals))
        out.block_until_ready()
        assert overlap.readback_stats() == 0


# ---------------------------------------------------------------------------
# sharded bank
# ---------------------------------------------------------------------------


class TestShardedBank:
    @pytest.mark.parametrize("n_dev", [1, 3, 8])
    def test_global_round_trip(self, n_dev, rng):
        mesh = entity_mesh(n_dev)
        spec = EntityShardSpec(n_dev, 41)
        bank = rng.normal(size=(41, 7)).astype(np.float32)
        sb = ShardedREBank.from_global(mesh, spec, bank)
        np.testing.assert_array_equal(np.asarray(sb.to_global()), bank)

    def test_per_device_bytes_scale_with_shards(self):
        """THE weak-scaling pin: at 8 shards each device holds ~1/8 of
        the replicated bank's bytes (exact here — E divides 8)."""
        E, d = 1024, 16
        replicated_bytes = E * d * 4
        sb = ShardedREBank.zeros(
            entity_mesh(8), EntityShardSpec(8, E), d
        )
        assert sb.per_device_bytes() == replicated_bytes // 8

    def test_hash_placement(self):
        """Entity e lives on shard e % n at local row e // n."""
        spec = EntityShardSpec(4, 10)
        rows = spec.sharded_row_of(np.arange(10))
        e_loc = spec.rows_per_shard
        assert e_loc == 3
        np.testing.assert_array_equal(
            rows, (np.arange(10) % 4) * e_loc + np.arange(10) // 4
        )


# ---------------------------------------------------------------------------
# sharded update parity
# ---------------------------------------------------------------------------


class TestShardedUpdateParity:
    @pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
    def test_update_and_score_match_replicated(self, n_dev, rng):
        """The tentpole parity: sharded update_bank == replicated
        update_bank (converged entities freeze bitwise under vmap, so
        the split-by-hash grouping cannot perturb any entity's solve),
        tracker aggregates equal, routed scores equal replicated
        scores."""
        ds, red = _synthetic_re()
        resid = jnp.asarray(
            ds.offsets + (rng.normal(size=ds.num_rows) * 0.05).astype(
                np.float32
            )
        )
        ref_bank, ref_tracker = _problem().update_bank(
            jnp.zeros((red.num_entities, red.local_dim), jnp.float32),
            red, residual_offsets=resid,
        )
        ref_scores = np.asarray(score_random_effect(ref_bank, red))

        pod = PodRandomEffectProblem(_problem(), entity_mesh(n_dev))
        new_bank, tracker = pod.update_bank(
            pod.init_bank(red), red, residual_offsets=resid
        )
        np.testing.assert_allclose(
            np.asarray(new_bank.to_global()), np.asarray(ref_bank),
            atol=1e-5, rtol=1e-5,
        )
        assert tracker.num_entities == ref_tracker.num_entities
        assert tracker.iterations_mean == ref_tracker.iterations_mean
        assert tracker.reason_counts == ref_tracker.reason_counts
        np.testing.assert_allclose(
            np.asarray(pod.score(new_bank, red)), ref_scores,
            atol=1e-5, rtol=1e-5,
        )

    def test_variances_match_replicated(self, rng):
        ds, red = _synthetic_re()
        resid = jnp.asarray(ds.offsets)
        ref_bank, _, ref_var = _problem().update_bank(
            jnp.zeros((red.num_entities, red.local_dim), jnp.float32),
            red, residual_offsets=resid, with_variances=True,
        )
        pod = PodRandomEffectProblem(_problem(), entity_mesh(4))
        bank, _, var = pod.update_bank(
            pod.init_bank(red), red, residual_offsets=resid,
            with_variances=True,
        )
        np.testing.assert_allclose(
            np.asarray(bank.to_global()), np.asarray(ref_bank),
            atol=1e-5, rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(var.to_global()), np.asarray(ref_var),
            atol=1e-5, rtol=1e-5,
        )

    def test_tron_kind_matches_replicated(self, rng):
        """Solver-family selection rides the GLOBAL bucket shapes, so a
        TRON config exercises the same kind on both paths."""
        from photon_ml_tpu.optim.config import RegularizationType

        ds, red = _synthetic_re(n=127, E=13)
        resid = jnp.asarray(ds.offsets)

        def tron_problem():
            return RandomEffectOptimizationProblem(
                LOGISTIC, OptimizerConfig(
                    max_iter=4, optimizer_type=OptimizerType.TRON
                ),
                RegularizationContext(RegularizationType.L2),
                reg_weight=0.3,
            )

        ref_bank, _ = tron_problem().update_bank(
            jnp.zeros((red.num_entities, red.local_dim), jnp.float32),
            red, residual_offsets=resid,
        )
        pod = PodRandomEffectProblem(tron_problem(), entity_mesh(4))
        bank, _ = pod.update_bank(
            pod.init_bank(red), red, residual_offsets=resid
        )
        np.testing.assert_allclose(
            np.asarray(bank.to_global()), np.asarray(ref_bank),
            atol=2e-5, rtol=1e-4,
        )

    def test_update_requires_residual_vector(self):
        _, red = _synthetic_re()
        pod = PodRandomEffectProblem(_problem(), entity_mesh(2))
        with pytest.raises(ValueError, match="row-aligned"):
            pod.update_bank(pod.init_bank(red), red)

    def test_base_problem_must_be_meshless(self):
        with pytest.raises(ValueError, match="mesh-less"):
            PodRandomEffectProblem(
                _problem(mesh=entity_mesh(2)), entity_mesh(2)
            )


# ---------------------------------------------------------------------------
# routed-path readback discipline
# ---------------------------------------------------------------------------


class TestRoutedPathDiscipline:
    def test_zero_host_gathers_in_update_and_score(self, rng):
        """The acceptance pin: the residual-routing hot path (route in,
        sharded solve, score, route back) crosses the host boundary
        exactly ZERO times — every device_get in the package is counted
        through the overlap seam."""
        ds, red = _synthetic_re()
        pod = PodRandomEffectProblem(_problem(), entity_mesh(8))
        pod.prepare(red)  # stage tables/blocks outside the counted window
        bank = pod.init_bank(red)
        resid = jnp.asarray(ds.offsets)
        with overlap.overlap_scope(True):
            overlap.reset_readback_stats()
            bank, tracker = pod.update_bank(
                bank, red, residual_offsets=resid, defer_tracker=True
            )
            scores = pod.score(bank, red)
            scores.block_until_ready()
            jax.block_until_ready(bank.data)
            assert overlap.readback_stats() == 0
            # the deferred tracker fetch is the CD loop's ONE batched
            # readback — forcing it is exactly one counted crossing
            overlap.fetch_all([tracker.deferred])
            assert overlap.readback_stats() == 1

    def test_cd_loop_one_readback_per_iteration(self, rng):
        ds, red = _synthetic_re(n=96, E=11)
        cd = _build_cd(ds, red, entity_mesh(4))
        with overlap.overlap_scope(True):
            overlap.reset_readback_stats()
            cd.run(2)
            assert overlap.readback_stats() == 2


# ---------------------------------------------------------------------------
# CD parity + weak-scaling bytes
# ---------------------------------------------------------------------------


def _build_cd(ds, red, pod_mesh=None, num_fe_iter=5):
    task = TaskType.LOGISTIC_REGRESSION
    loss = loss_for_task(task)
    fe_problem = create_glm_problem(
        task, ds.shards["s"].dim, config=OptimizerConfig(max_iter=num_fe_iter)
    )
    coords = {
        "fixed": FixedEffectCoordinate(
            name="fixed", dataset=ds, problem=fe_problem,
            feature_shard_id="s", reg_weight=0.1,
        ),
    }
    rep = _problem()
    if pod_mesh is None:
        coords["per-user"] = RandomEffectCoordinate(
            name="per-user", dataset=ds, re_dataset=red, problem=rep
        )
    else:
        coords["per-user"] = PodRandomEffectCoordinate(
            name="per-user", dataset=ds, re_dataset=red, problem=rep,
            mesh=pod_mesh,
        )
    return CoordinateDescent(coords, ds, task)


class TestShardedCDParity:
    @pytest.mark.parametrize("n_dev", [2, 8])
    def test_full_cd_matches_replicated(self, n_dev, rng):
        ds, red = _synthetic_re(n=96, E=11)
        ref = _build_cd(ds, red).run(2)
        res = _build_cd(ds, red, entity_mesh(n_dev)).run(2)
        np.testing.assert_allclose(
            res.objective_history, ref.objective_history, rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(res.model.models["per-user"].bank),
            np.asarray(ref.model.models["per-user"].bank),
            atol=1e-3, rtol=1e-3,
        )
        np.testing.assert_allclose(
            np.asarray(res.model.models["fixed"].model.means),
            np.asarray(ref.model.models["fixed"].model.means),
            atol=1e-3, rtol=1e-3,
        )


class TestWeakScalingBytes:
    def test_per_device_bank_bytes_bounded_at_8_shards(self):
        """Acceptance: at N=8, per-device RE bank + optimizer-state
        bytes <= (1/8 + slack) of the replicated path for the same
        model. Slack covers hash padding (<= one row per shard) only."""
        E, d = 1000, 32  # deliberately NOT divisible by 8
        n_dev = 8
        replicated = E * d * 4
        spec = EntityShardSpec(n_dev, E)
        mesh = entity_mesh(n_dev)
        bank = ShardedREBank.zeros(mesh, spec, d)
        var = ShardedREBank.zeros(mesh, spec, d)
        got = per_device_bytes(bank, var)
        pad_slack = n_dev * spec.rows_per_shard * d * 4 - replicated
        assert got <= (2 * replicated) // n_dev + pad_slack + 4096
        # and the sharded total equals the padded bank, not N copies
        total = sum(
            int(s.data.nbytes)
            for a in (bank.data, var.data)
            for s in a.addressable_shards
        )
        assert total == 2 * n_dev * spec.rows_per_shard * d * 4

    def test_dataset_blocks_shard_too(self, rng):
        """The staged per-entity data (solver blocks + scoring slots)
        also scales down per device: at 8 shards each device stages
        < 40% of what 1 shard stages (padding keeps it above 1/8 at
        this tiny size)."""
        _, red = _synthetic_re(n=1024, E=128, d=8, k=4)
        v1 = PodRandomEffectProblem(_problem(), entity_mesh(1)).pod_view(red)
        v8 = PodRandomEffectProblem(_problem(), entity_mesh(8)).pod_view(red)
        assert (
            v8.per_device_data_bytes() < 0.4 * v1.per_device_data_bytes()
        )


# ---------------------------------------------------------------------------
# streaming x sharded
# ---------------------------------------------------------------------------


class TestStreamingSharded:
    def test_streamed_sharded_matches_streamed_replicated(
        self, tmp_path, rng
    ):
        """Streaming composes with entity sharding: same objectives,
        same final banks (the segment split by hash + psum chunk scoring
        reproduce the replicated streamed math bitwise-or-near)."""
        from test_streaming_game import (
            FE_DATA, RE_DATA, SHARDS, _combo, _write_game_files,
        )

        from photon_ml_tpu.game.streaming import train_streaming_game

        train = str(tmp_path / "train")
        _write_game_files(train, rng, n_files=2, rows_per_file=80)
        combo = _combo("30,1e-6,0.5,1,TRON,L2", "30,1e-6,1.0,1,LBFGS,L2")
        ref, _ = train_streaming_game(
            [train], SHARDS, FE_DATA, RE_DATA, combo,
            TaskType.LOGISTIC_REGRESSION, num_iterations=2,
            memory_budget_bytes=100 * 60,
        )
        res, extras = train_streaming_game(
            [train], SHARDS, FE_DATA, RE_DATA, combo,
            TaskType.LOGISTIC_REGRESSION, num_iterations=2,
            memory_budget_bytes=100 * 60,
            entity_mesh=entity_mesh(4),
        )
        assert extras["store"].count >= 2
        np.testing.assert_allclose(
            res.objective_history, ref.objective_history, rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(res.game_model.get_model("per-user").bank),
            np.asarray(ref.game_model.get_model("per-user").bank),
            atol=1e-5, rtol=1e-5,
        )

    def test_driver_streaming_sharded_end_to_end(self, tmp_path, rng):
        """--streaming --entity-shards through the real driver: same
        objective history as the replicated streamed driver run, model
        artifact round-trips."""
        from test_streaming_game import (
            FE_DATA, RE_DATA, SHARDS, _write_game_files,
        )

        from photon_ml_tpu.cli.game_training_driver import (
            GameTrainingDriver,
            GameTrainingParams,
        )
        from photon_ml_tpu.game.model_io import load_game_model

        train = str(tmp_path / "train")
        _write_game_files(train, rng, n_files=2, rows_per_file=80)

        def run(tag, entity_shards):
            params = GameTrainingParams(
                train_input_dirs=[train],
                output_dir=str(tmp_path / tag),
                task_type=TaskType.LOGISTIC_REGRESSION,
                feature_shards=SHARDS,
                fixed_effect_data_configs=dict(FE_DATA),
                fixed_effect_opt_configs={
                    "global": "30,1e-6,0.5,1,TRON,L2"
                },
                random_effect_data_configs=dict(RE_DATA),
                random_effect_opt_configs={
                    "per-user": "30,1e-6,1.0,1,LBFGS,L2"
                },
                num_iterations=2,
                streaming=True,
                stream_memory_budget=100 * 60,
                entity_shards=entity_shards,
            )
            GameTrainingDriver(params).run()
            return json.load(
                open(os.path.join(params.output_dir, "metrics.json"))
            )

        m_sharded = run("out-sharded", 4)
        m_ref = run("out-ref", None)
        np.testing.assert_allclose(
            m_sharded["objective_history"], m_ref["objective_history"],
            rtol=1e-6,
        )
        loaded = load_game_model(
            os.path.join(str(tmp_path / "out-sharded"), "best-model")
        )
        assert set(loaded.coordinate_names()) == {"global", "per-user"}

    def test_driver_in_memory_sharded_end_to_end(self, tmp_path, rng):
        """--entity-shards through the IN-MEMORY driver path (pod
        coordinates, lazy-bank model export, validation scoring):
        objective parity vs the replicated driver run, artifact
        round-trips."""
        from test_streaming_game import (
            FE_DATA, RE_DATA, SHARDS, _write_game_files,
        )

        from photon_ml_tpu.cli.game_training_driver import (
            GameTrainingDriver,
            GameTrainingParams,
        )
        from photon_ml_tpu.game.model_io import load_game_model

        train = str(tmp_path / "train")
        val = str(tmp_path / "val")
        _write_game_files(train, rng, n_files=1, rows_per_file=120)
        _write_game_files(val, rng, n_files=1, rows_per_file=80)

        def run(tag, entity_shards):
            params = GameTrainingParams(
                train_input_dirs=[train],
                validate_input_dirs=[val],
                output_dir=str(tmp_path / tag),
                task_type=TaskType.LOGISTIC_REGRESSION,
                feature_shards=SHARDS,
                fixed_effect_data_configs=dict(FE_DATA),
                fixed_effect_opt_configs={
                    "global": "20,1e-6,0.5,1,LBFGS,L2"
                },
                random_effect_data_configs=dict(RE_DATA),
                random_effect_opt_configs={
                    "per-user": "20,1e-6,1.0,1,LBFGS,L2"
                },
                num_iterations=2,
                distributed="off",
                entity_shards=entity_shards,
            )
            GameTrainingDriver(params).run()
            return json.load(
                open(os.path.join(params.output_dir, "metrics.json"))
            )

        m_sharded = run("mem-sharded", -1)  # all 8 virtual devices
        m_ref = run("mem-ref", None)
        np.testing.assert_allclose(
            m_sharded["objective_history"], m_ref["objective_history"],
            rtol=1e-5,
        )
        assert m_sharded["validation_history"]
        loaded = load_game_model(
            os.path.join(str(tmp_path / "mem-sharded"), "best-model")
        )
        assert set(loaded.coordinate_names()) == {"global", "per-user"}

    def test_streaming_sharded_rejects_variances(self, tmp_path):
        from photon_ml_tpu.game.streaming import (
            StreamingRandomEffectCoordinate,
        )

        with pytest.raises(ValueError, match="compute_variances"):
            StreamingRandomEffectCoordinate(
                name="x", store=None, spilled=None,
                problem=_problem(compute_variances=True),
                config=RandomEffectDataConfiguration(
                    "user", "s", projector_type=ProjectorType.IDENTITY
                ),
                local_dim=4,
                mesh=entity_mesh(2),
            )


# ---------------------------------------------------------------------------
# driver policy
# ---------------------------------------------------------------------------


class TestEntityShardPolicy:
    def test_resolve_entity_shards(self):
        from photon_ml_tpu.training import resolve_entity_shards

        assert resolve_entity_shards(None, num_devices=8) is None
        assert resolve_entity_shards(0, num_devices=8) is None
        assert resolve_entity_shards(-1, num_devices=8) == 8
        assert resolve_entity_shards(1, num_devices=8) == 1
        assert resolve_entity_shards(4, num_devices=8) == 4
        with pytest.raises(ValueError, match="out of range"):
            resolve_entity_shards(9, num_devices=8)

    def test_driver_rejects_entity_shards_with_factored(self):
        from photon_ml_tpu.cli.game_training_driver import GameTrainingParams
        from photon_ml_tpu.game.config import (
            FactoredRandomEffectConfiguration,
            FeatureShardConfiguration,
            FixedEffectDataConfiguration,
        )

        params = GameTrainingParams(
            train_input_dirs=["x"],
            output_dir="y",
            feature_shards=[
                FeatureShardConfiguration("g", ["features"])
            ],
            fixed_effect_data_configs={
                "fe": FixedEffectDataConfiguration("g")
            },
            fixed_effect_opt_configs={"fe": "10,1e-6,0.1,1,LBFGS,L2"},
            random_effect_data_configs={
                "re": RandomEffectDataConfiguration("user", "g")
            },
            random_effect_opt_configs={"re": "10,1e-6,0.1,1,LBFGS,L2"},
            factored_re_configs={
                "re": FactoredRandomEffectConfiguration(2, 1)
            },
            entity_shards=4,
        )
        with pytest.raises(ValueError, match="plain random-effect"):
            params.validate()

    @staticmethod
    def _driver(out_dir, **kw):
        from photon_ml_tpu.cli.game_training_driver import (
            GameTrainingDriver,
            GameTrainingParams,
        )
        from photon_ml_tpu.game.config import (
            FeatureShardConfiguration,
            FixedEffectDataConfiguration,
        )

        return GameTrainingDriver(GameTrainingParams(
            train_input_dirs=["x"],
            output_dir=str(out_dir),
            feature_shards=[
                FeatureShardConfiguration("g", ["features"])
            ],
            fixed_effect_data_configs={
                "fe": FixedEffectDataConfiguration("g")
            },
            fixed_effect_opt_configs={"fe": "10,1e-6,0.1,1,LBFGS,L2"},
            random_effect_data_configs={
                "re": RandomEffectDataConfiguration("user", "g")
            },
            random_effect_opt_configs={"re": "10,1e-6,0.1,1,LBFGS,L2"},
            **kw,
        ))

    def test_partial_entity_mesh_restricts_data_mesh(self, tmp_path):
        """--entity-shards N < visible devices: the driver's data and FE
        meshes must span EXACTLY the pod entity device set. CD row
        currency (scores, residuals) is committed to the entity
        devices, and jit refuses `residual + new_score` across two
        device sets (regression: distributed=auto + entity_shards=2
        used to build an 8-device data mesh next to the 2-device pod
        mesh and crash in the first CD iteration)."""
        d = self._driver(
            tmp_path / "a", distributed="auto", entity_shards=2
        )
        pod_ids = [dev.id for dev in d._entity_mesh().devices.flat]
        assert [dev.id for dev in d._mesh().devices.flat] == pod_ids
        assert [dev.id for dev in d._fe_mesh().devices.flat] == pod_ids

        # full entity mesh: the data mesh spans all devices unchanged
        d = self._driver(
            tmp_path / "b", distributed="auto", entity_shards=-1
        )
        assert d._mesh().devices.size == len(jax.devices())

        # 1-entity-shard run is effectively single-device: no data mesh
        # (unmeshed FE scores follow the pod placement)
        d = self._driver(
            tmp_path / "c", distributed="auto", entity_shards=1
        )
        assert d._mesh() is None

        # feature mode: the 2-D (data, model) FE mesh restricts too
        d = self._driver(
            tmp_path / "d",
            distributed="feature", entity_shards=4, model_shards=2,
        )
        fe = d._fe_mesh()
        assert sorted(dev.id for dev in fe.devices.flat) == sorted(
            dev.id for dev in d._entity_mesh().devices.flat
        )
        assert fe.shape["model"] == 2
        with pytest.raises(ValueError, match="does not divide"):
            self._driver(
                tmp_path / "e",
                distributed="feature", entity_shards=3, model_shards=2,
            )._fe_mesh()

    def test_driver_auto_distributed_partial_shards(self, tmp_path, rng):
        """The regression flow end to end: in-memory driver,
        distributed=auto, entity_shards=2 of 8."""
        from test_streaming_game import (
            FE_DATA, RE_DATA, SHARDS, _write_game_files,
        )

        from photon_ml_tpu.cli.game_training_driver import (
            GameTrainingDriver,
            GameTrainingParams,
        )

        train = str(tmp_path / "train")
        _write_game_files(train, rng, n_files=1, rows_per_file=120)
        params = GameTrainingParams(
            train_input_dirs=[train],
            output_dir=str(tmp_path / "out"),
            task_type=TaskType.LOGISTIC_REGRESSION,
            feature_shards=SHARDS,
            fixed_effect_data_configs=dict(FE_DATA),
            fixed_effect_opt_configs={
                "global": "20,1e-6,0.5,1,LBFGS,L2"
            },
            random_effect_data_configs=dict(RE_DATA),
            random_effect_opt_configs={
                "per-user": "20,1e-6,1.0,1,LBFGS,L2"
            },
            num_iterations=2,
            distributed="auto",
            entity_shards=2,
        )
        GameTrainingDriver(params).run()
        m = json.load(
            open(os.path.join(params.output_dir, "metrics.json"))
        )
        h = m["objective_history"]
        assert len(h) == 2 and h[1] <= h[0] + 1e-6


# ---------------------------------------------------------------------------
# serving: one entity shard of a sharded model
# ---------------------------------------------------------------------------


class TestServingEntityShard:
    def _full_and_shards(self, n_shards=4, E=23, d=6):
        from photon_ml_tpu.serving.model_bank import bank_from_arrays

        rng = np.random.default_rng(3)
        ids = sorted(f"user{i:04d}" for i in range(E))
        bank = rng.normal(size=(E, d)).astype(np.float32)
        kw = dict(
            fixed=[("fe", "g", rng.normal(size=(d,)).astype(np.float32))],
            random=[("re", "user", "g", bank, ids)],
            shard_widths={"g": 4},
            entity_pad_to=8,
        )
        full = bank_from_arrays(**kw)
        shards = [
            bank_from_arrays(**kw, entity_shard=(s, n_shards))
            for s in range(n_shards)
        ]
        return ids, bank, full, shards

    def test_owned_rows_match_full_bank(self):
        ids, bank, full, shards = self._full_and_shards()
        for s, sb in enumerate(shards):
            idx = sb.entity_rows["user"]
            assert idx.shard == (s, 4)
            for code, raw in enumerate(ids):
                row = idx.row_of(raw)
                if code % 4 == s:
                    assert row >= 0
                    np.testing.assert_array_equal(
                        np.asarray(sb.arrays["re"][row]), bank[code]
                    )
                else:
                    # unknown-shard entity: row -1 -> FE-only scoring,
                    # the batcher's existing masked-row semantics
                    assert row == -1

    def test_shards_partition_the_entity_set(self):
        ids, _, full, shards = self._full_and_shards()
        owned = [set(sb.entity_rows["user"].ids) for sb in shards]
        union = set().union(*owned)
        assert union == set(ids)
        assert sum(len(o) for o in owned) == len(ids)  # disjoint

    def test_shard_bank_is_smaller(self):
        _, _, full, shards = self._full_and_shards()
        full_bytes = full.device_bytes()
        for sb in shards:
            assert sb.device_bytes() < full_bytes

    def test_sharded_artifact_load_scores_fe_only_off_shard(self, rng):
        """End-to-end through build_model_bank + the micro-batcher: a
        server loading ONE entity shard of a trained GAME artifact
        scores owned entities BITWISE like the full bank and FE-only
        (bitwise the unknown-entity path) for entities another shard
        owns."""
        from test_serving import make_bank, synth_model, synth_records

        from photon_ml_tpu.game.data import build_game_dataset
        from photon_ml_tpu.serving.batcher import (
            MicroBatcher,
            requests_from_dataset,
        )
        from photon_ml_tpu.serving.programs import ServingPrograms

        recs = synth_records(rng)
        from test_serving import SHARDS as SERVING_SHARDS

        ds = build_game_dataset(recs, SERVING_SHARDS, ["userId"])
        lm = synth_model(rng, drop_user=False)
        full = make_bank(lm, ds)
        shard0 = make_bank(lm, ds, entity_shard=(0, 2))

        def score_all(bank_, reqs):
            programs = ServingPrograms((1, 8, 64))
            programs.ensure_compiled(bank_)
            with MicroBatcher(lambda: bank_, programs) as mb:
                futs = [mb.submit(r) for r in reqs]
                return np.asarray([f.result() for f in futs], np.float32)

        reqs = requests_from_dataset(ds, full)
        full_scores = score_all(full, reqs)
        shard_scores = score_all(shard0, reqs)
        # FE-only reference: the same rows with their entity UNKNOWN
        import dataclasses

        fe_reqs = [
            dataclasses.replace(r, entity_ids={"userId": "no-such-user"})
            for r in reqs
        ]
        fe_only = score_all(full, fe_reqs)

        owned_ids = set(shard0.entity_rows["userId"].ids)
        for i, r in enumerate(reqs):
            raw = r.entity_ids.get("userId")
            if raw in owned_ids:
                assert shard_scores[i] == full_scores[i]
            else:
                assert shard_scores[i] == fe_only[i]
        # both cases actually occur in the trace
        assert any(r.entity_ids.get("userId") in owned_ids for r in reqs)
        assert any(
            r.entity_ids.get("userId") not in owned_ids for r in reqs
        )
