"""The shared entity-ownership rule (photon_ml_tpu/ownership.py):
property tests pinning that every plane that places entities on shards
— pod training placement, the in-jit shuffle owner computation, the
serving shard loader, and the routing tier — agrees for random ids.
A disagreement between any two of these would silently serve (or
train) a coefficient on the wrong host, so the agreement IS the
contract, not an implementation detail.
"""

import numpy as np
import pytest

from photon_ml_tpu import ownership
from photon_ml_tpu.game.pod import EntityShardSpec, entity_shard_of
from photon_ml_tpu.serving.model_bank import shard_entity_ids

SHARD_COUNTS = (1, 2, 3, 4, 8)


@pytest.fixture
def codes(rng):
    return rng.integers(0, 10_000, size=512).astype(np.int64)


class TestRule:
    def test_owner_and_local_row_roundtrip(self, codes):
        for n in SHARD_COUNTS:
            owner = ownership.owner_of(codes, n)
            local = ownership.local_row_of(codes, n)
            assert np.all((owner >= 0) & (owner < n))
            assert np.array_equal(owner * 1 + 0, codes % n)
            # (owner, local) uniquely reconstructs the code
            assert np.array_equal(local * n + owner, codes)

    def test_scalar_and_array_agree(self, codes):
        for n in SHARD_COUNTS:
            arr = ownership.owner_of(codes, n)
            for i in (0, 17, 101):
                assert int(arr[i]) == ownership.owner_of(int(codes[i]), n)

    def test_validate_entity_shard(self):
        assert ownership.validate_entity_shard(None) is None
        assert ownership.validate_entity_shard((2, 4)) == (2, 4)
        for bad in ((4, 4), (-1, 4), (0, 0), (1, -2)):
            with pytest.raises(ValueError, match="entity_shard"):
                ownership.validate_entity_shard(bad)


class TestCallSitesAgree:
    def test_pod_placement_matches_ownership(self, codes):
        """game/pod.py's entity_shard_of IS the shared rule."""
        for n in SHARD_COUNTS:
            assert np.array_equal(
                entity_shard_of(codes, n), ownership.owner_of(codes, n)
            )

    def test_pod_sharded_row_matches_ownership(self, codes):
        for n in SHARD_COUNTS:
            spec = EntityShardSpec(
                num_shards=n, num_entities=int(codes.max()) + 1
            )
            assert np.array_equal(
                spec.sharded_row_of(codes),
                ownership.sharded_row_of(codes, n, spec.rows_per_shard),
            )
            assert np.array_equal(
                spec.local_of(codes), ownership.local_row_of(codes, n)
            )

    def test_shuffle_owner_matches_ownership(self, codes):
        """parallel/shuffle routes a row to the device the shared rule
        names (jnp path, traced the way entity_all_to_all computes it)."""
        import jax.numpy as jnp

        for n in SHARD_COUNTS:
            jcodes = jnp.asarray(codes)
            owner = jnp.where(
                jcodes >= 0, ownership.owner_of(jcodes, n), n
            )
            assert np.array_equal(
                np.asarray(owner), ownership.owner_of(codes, n)
            )

    def test_serving_shard_split_matches_pod_placement(self, rng):
        """The serving loader's id-list split selects EXACTLY the ids
        whose code (sorted position) the pod rule assigns to that
        shard — for random id universes and every shard count."""
        n_ids = int(rng.integers(1, 400))
        ids = sorted({f"e{int(x)}" for x in rng.integers(0, 10**6, n_ids)})
        positions = np.arange(len(ids), dtype=np.int64)
        for n in SHARD_COUNTS:
            owners = entity_shard_of(positions, n)
            for s in range(n):
                expect = [ids[i] for i in np.nonzero(owners == s)[0]]
                assert shard_entity_ids(ids, (s, n)) == expect
            # the shards partition the universe: nothing lost, nothing
            # duplicated
            union = [
                x for s in range(n) for x in shard_entity_ids(ids, (s, n))
            ]
            assert sorted(union) == ids

    def test_owned_positions_partition(self):
        for total in (0, 1, 7, 256):
            for n in SHARD_COUNTS:
                seen = sorted(
                    p
                    for s in range(n)
                    for p in ownership.owned_positions(total, s, n)
                )
                assert seen == list(range(total))
