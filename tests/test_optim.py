"""Optimizer tests: closed-form quadratics, GLM convergence, L1 sparsity,
box constraints, vmap-ability.

Mirrors the reference's unit strategy (optimization/LBFGSTest, OWLQNTest,
TRONTest against `TestObjective` closed forms) — validator-style checks, no
golden numbers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.batch import make_dense_batch
from photon_ml_tpu.ops.losses import LOGISTIC
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim import (
    BoxConstraints,
    GLMOptimizationConfiguration,
    NOT_CONVERGED,
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    make_optimizer,
    minimize_lbfgs,
    minimize_owlqn,
    minimize_tron,
    validate_optimizer_choice,
)


def quad_vg(center, scales):
    center = jnp.asarray(center)
    scales = jnp.asarray(scales)

    def vg(w):
        d = w - center
        return 0.5 * jnp.sum(scales * d * d), scales * d

    return vg


def quad_hvp(scales):
    scales = jnp.asarray(scales)
    return lambda w, d: scales * d


CENTER = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
SCALES = np.array([1.0, 4.0, 0.5, 2.0], np.float32)


class TestLBFGS:
    def test_quadratic(self):
        res = minimize_lbfgs(quad_vg(CENTER, SCALES), jnp.zeros(4))
        np.testing.assert_allclose(np.asarray(res.coefficients), CENTER, atol=1e-4)
        assert int(res.reason) != NOT_CONVERGED

    def test_tracker_monotone(self):
        res = minimize_lbfgs(quad_vg(CENTER, SCALES), jnp.zeros(4))
        n = int(res.tracker.count)
        vals = np.asarray(res.tracker.values)[:n]
        assert vals[-1] <= vals[0]
        assert n == int(res.iterations) + 1

    def test_max_iter(self):
        res = minimize_lbfgs(quad_vg(CENTER, SCALES), jnp.zeros(4), max_iter=2)
        assert int(res.iterations) <= 2

    def test_box_constraints(self):
        box = BoxConstraints(
            lower=jnp.array([-0.5, -0.5, -0.5, -0.5]),
            upper=jnp.array([0.5, 0.5, 0.5, 0.5]),
        )
        res = minimize_lbfgs(quad_vg(CENTER, SCALES), jnp.zeros(4), box=box)
        w = np.asarray(res.coefficients)
        assert np.all(w >= -0.5 - 1e-6) and np.all(w <= 0.5 + 1e-6)
        # Unconstrained optimum is outside the box on dims 0-2 → clamp there.
        np.testing.assert_allclose(w[0], 0.5, atol=1e-3)
        np.testing.assert_allclose(w[1], -0.5, atol=1e-3)

    def test_jit_and_vmap(self):
        centers = jnp.stack([jnp.asarray(CENTER), -jnp.asarray(CENTER)])

        @jax.jit
        @jax.vmap
        def solve(center):
            return minimize_lbfgs(quad_vg(center, SCALES), jnp.zeros(4)).coefficients

        out = solve(centers)
        np.testing.assert_allclose(np.asarray(out), np.asarray(centers), atol=1e-3)

    def test_zero_gradient_start(self):
        res = minimize_lbfgs(quad_vg(CENTER, SCALES), jnp.asarray(CENTER))
        np.testing.assert_allclose(np.asarray(res.coefficients), CENTER, atol=1e-6)


class TestOWLQN:
    def test_l1_produces_sparsity(self):
        # min 0.5||w - c||^2 + l1*||w||_1 has closed form soft(c, l1).
        vg = quad_vg(CENTER, np.ones(4, np.float32))
        res = minimize_owlqn(vg, jnp.zeros(4), l1_weight=0.7)
        expect = np.sign(CENTER) * np.maximum(np.abs(CENTER) - 0.7, 0.0)
        np.testing.assert_allclose(np.asarray(res.coefficients), expect, atol=1e-3)
        assert np.asarray(res.coefficients)[3] == pytest.approx(0.0, abs=1e-6)

    def test_zero_l1_matches_lbfgs(self):
        vg = quad_vg(CENTER, SCALES)
        res = minimize_owlqn(vg, jnp.zeros(4), l1_weight=0.0)
        np.testing.assert_allclose(np.asarray(res.coefficients), CENTER, atol=1e-3)

    def test_l1_mask_exempts_intercept(self):
        vg = quad_vg(CENTER, np.ones(4, np.float32))
        mask = jnp.array([1.0, 1.0, 1.0, 0.0])
        res = minimize_owlqn(vg, jnp.zeros(4), l1_weight=0.7, l1_mask=mask)
        w = np.asarray(res.coefficients)
        np.testing.assert_allclose(w[3], CENTER[3], atol=1e-3)  # unpenalized


class TestTRON:
    def test_quadratic(self):
        res = minimize_tron(
            quad_vg(CENTER, SCALES), quad_hvp(SCALES), jnp.zeros(4)
        )
        np.testing.assert_allclose(np.asarray(res.coefficients), CENTER, atol=1e-4)

    def test_logistic_matches_lbfgs(self, rng):
        n, d = 256, 8
        x = rng.normal(size=(n, d)).astype(np.float32)
        w_true = rng.normal(size=d).astype(np.float32)
        y = (1 / (1 + np.exp(-x @ w_true)) > rng.uniform(size=n)).astype(np.float32)
        batch = make_dense_batch(x, y)
        obj = GLMObjective(LOGISTIC, d)
        vg = lambda w: obj.value_and_gradient(w, batch, l2_weight=0.1)
        hvp = lambda w, dd: obj.hessian_vector(w, dd, batch, l2_weight=0.1)
        r_tron = minimize_tron(vg, hvp, jnp.zeros(d), max_iter=50)
        r_lbfgs = minimize_lbfgs(vg, jnp.zeros(d))
        np.testing.assert_allclose(
            np.asarray(r_tron.coefficients), np.asarray(r_lbfgs.coefficients),
            atol=2e-3,
        )

    def test_vmap(self):
        centers = jnp.stack([jnp.asarray(CENTER), 2 * jnp.asarray(CENTER)])

        @jax.vmap
        def solve(c):
            return minimize_tron(quad_vg(c, SCALES), quad_hvp(SCALES), jnp.zeros(4)).coefficients

        np.testing.assert_allclose(np.asarray(solve(centers)), np.asarray(centers), atol=1e-3)


class TestHostTronBox:
    def test_box_projected_step_converges_to_constrained_optimum(self):
        """Active box constraints: the trust-region test must use a
        quadratic model of the PROJECTED step (host_tron recomputes
        prered via one extra Hv when projection alters s), so the solver
        still walks to the constrained optimum instead of collapsing the
        radius on inconsistent actred/prered ratios."""
        from photon_ml_tpu.optim.host_tron import minimize_tron_host

        center = jnp.asarray([2.0, -3.0, 0.25, 1.5], jnp.float32)
        scales = jnp.asarray([1.0, 4.0, 0.5, 2.0], jnp.float32)
        box = BoxConstraints(
            lower=jnp.full((4,), -0.5, jnp.float32),
            upper=jnp.full((4,), 0.5, jnp.float32),
        )
        res = minimize_tron_host(
            quad_vg(center, scales),
            quad_hvp(scales),
            jnp.zeros(4),
            max_iter=100,
            tol=1e-10,
            box=box,
        )
        # separable quadratic: the constrained optimum is the clipped center
        expected = np.clip(np.asarray(center), -0.5, 0.5)
        np.testing.assert_allclose(
            np.asarray(res.coefficients), expected, atol=1e-3
        )
        assert int(res.reason) != NOT_CONVERGED

    def test_unconstrained_matches_in_jit_tron(self):
        from photon_ml_tpu.optim.host_tron import minimize_tron_host

        res = minimize_tron_host(
            quad_vg(CENTER, SCALES), quad_hvp(SCALES), jnp.zeros(4)
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients), CENTER, atol=1e-4
        )


class TestFactory:
    def test_tron_l1_rejected(self):
        with pytest.raises(ValueError):
            validate_optimizer_choice(
                OptimizerConfig(OptimizerType.TRON),
                RegularizationContext(RegularizationType.L1),
            )

    def test_tron_no_hessian_rejected(self):
        with pytest.raises(ValueError):
            validate_optimizer_choice(
                OptimizerConfig(OptimizerType.TRON),
                RegularizationContext(RegularizationType.NONE),
                loss_has_hessian=False,
            )

    def test_lbfgs_l1_is_owlqn(self):
        opt = make_optimizer(
            OptimizerConfig(OptimizerType.LBFGS),
            RegularizationContext(RegularizationType.L1),
        )
        vg = quad_vg(CENTER, np.ones(4, np.float32))
        res = opt(vg, jnp.zeros(4), l1_weight=0.7)
        expect = np.sign(CENTER) * np.maximum(np.abs(CENTER) - 0.7, 0.0)
        np.testing.assert_allclose(np.asarray(res.coefficients), expect, atol=1e-3)

    def test_elastic_net_split(self):
        ctx = RegularizationContext(RegularizationType.ELASTIC_NET, 0.25)
        l1, l2 = ctx.split(4.0)
        assert l1 == pytest.approx(1.0) and l2 == pytest.approx(3.0)

    def test_config_string_roundtrip(self):
        cfg = GLMOptimizationConfiguration.parse("50,1e-6,0.3,0.5,TRON,L2")
        assert cfg.optimizer_config.max_iter == 50
        assert cfg.optimizer_config.optimizer_type == OptimizerType.TRON
        assert cfg.regularization.reg_type == RegularizationType.L2
        assert cfg.reg_weight == pytest.approx(0.3)
        assert cfg.down_sampling_rate == pytest.approx(0.5)
        cfg2 = GLMOptimizationConfiguration.parse(cfg.render())
        assert cfg2 == cfg

    def test_bad_config_strings(self):
        for s in ["1,2,3", "0,1e-6,0,1,LBFGS,NONE", "10,1e-6,-1,1,LBFGS,NONE",
                  "10,1e-6,0,0,LBFGS,NONE", "10,1e-6,0,1,ADAM,NONE"]:
            with pytest.raises((ValueError, KeyError)):
                GLMOptimizationConfiguration.parse(s)


class TestRegressions:
    def test_zero_gradient_start_reports_gradient_convergence(self):
        from photon_ml_tpu.optim import GRADIENT_WITHIN_TOLERANCE
        res = minimize_lbfgs(quad_vg(CENTER, SCALES), jnp.asarray(CENTER))
        assert int(res.reason) == GRADIENT_WITHIN_TOLERANCE
        assert int(res.iterations) == 0

    def test_owlqn_box_constrained_elastic_net(self):
        # The reference's OWLQN subclasses LBFGS and inherits the
        # hypercube projection (OWLQN.scala:43-91, LBFGS.scala:77), so
        # box + L1/elastic-net is a supported combination: the iterate
        # must converge INSIDE the box with the L1 shrinkage applied.
        box = BoxConstraints(
            lower=jnp.asarray([-0.5, -0.5, -0.5, -0.5]),
            upper=jnp.asarray([0.5, 0.5, 0.5, 0.5]),
        )
        optimize = make_optimizer(
            OptimizerConfig(OptimizerType.LBFGS),
            RegularizationContext(RegularizationType.ELASTIC_NET, 0.5),
            box=box,
        )
        res = optimize(quad_vg(CENTER, SCALES), jnp.zeros(4), l1_weight=0.05)
        w = np.asarray(res.coefficients)
        assert np.all(w >= -0.5 - 1e-6) and np.all(w <= 0.5 + 1e-6)
        # CENTER dims outside the box clamp to the boundary (minus L1
        # shrinkage pressure, which cannot push them back inside by more
        # than l1/scale); dims inside shrink toward zero.
        unconstrained = minimize_owlqn(
            quad_vg(CENTER, SCALES), jnp.zeros(4), 0.05
        )
        w_un = np.asarray(unconstrained.coefficients)
        expected = np.clip(w_un, -0.5, 0.5)
        np.testing.assert_allclose(w, expected, atol=0.05)
