"""Tiled sparse kernel tests (interpret mode on CPU): schedule invariants
and exact agreement with the scatter/gather GLMObjective on random
problems, including duplicates, skewed (intercept-like) features and
multi-window shapes.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.data.batch import make_sparse_batch
from photon_ml_tpu.ops.losses import LOGISTIC, LINEAR
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.tiled_sparse import (
    TileParams,
    TiledGLMObjective,
    tiled_batch_from_sparse,
)

PARAMS = TileParams(s_hi=8, s_lo=8, chunk=32)  # window 64, tiny for tests


def random_problem(rng, n=100, d=150, k=6, intercept=True):
    rows, labels = [], []
    for i in range(n):
        nnz = rng.integers(1, k + 1)
        ix = rng.choice(d - 1, size=nnz, replace=False).tolist()
        vs = rng.normal(size=nnz).tolist()
        if intercept:
            ix.append(d - 1)  # intercept-like skewed feature in EVERY row
            vs.append(1.0)
        labels.append(float(rng.uniform() > 0.5))
        rows.append((ix, vs))
    return make_sparse_batch(rows, labels, weights=rng.uniform(0.5, 2.0, n)), d


class TestSchedule:
    def test_entries_preserved(self, rng):
        batch, d = random_problem(rng)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        # every nonzero entry appears exactly once in each schedule
        # (chunk slots + spill tail together)
        nnz = int(np.count_nonzero(np.asarray(batch.values)))
        for sched in (tb.z_sched, tb.g_sched):
            assert (
                np.count_nonzero(sched.vals)
                + np.count_nonzero(sched.spill_vals)
            ) == nnz
        # monotone output blocks
        z_out = np.asarray(tb.z_sched.step_out)
        g_out = np.asarray(tb.g_sched.step_out)
        assert np.all(np.diff(z_out) >= 0)
        assert np.all(np.diff(g_out) >= 0)
        # init flags exactly at block changes
        changes = np.nonzero(np.diff(z_out) > 0)[0] + 1
        inits = np.nonzero(np.asarray(tb.z_sched.step_init))[0]
        assert inits[0] == 0 and set(inits[1:].tolist()) == set(changes.tolist())

    def test_window_bounds(self, rng):
        batch, d = random_problem(rng)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        for sched in (tb.z_sched, tb.g_sched):
            assert int(sched.out_pos.max()) < PARAMS.window
            assert int(sched.in_pos.max()) < PARAMS.window
            assert int(sched.out_pos.min()) >= 0
            assert int(sched.in_pos.min()) >= 0


class TestAgainstReferenceObjective:
    def _pair(self, rng, **kw):
        batch, d = random_problem(rng, **kw)
        obj = GLMObjective(LOGISTIC, d)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        tobj = TiledGLMObjective(LOGISTIC, d, interpret=True, mxu="highest")
        return batch, obj, tobj, tb, d

    def test_value_and_gradient(self, rng):
        batch, obj, tobj, tb, d = self._pair(rng)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v0, g0 = obj.value_and_gradient(w, batch, 0.3)
        v1, g1 = tobj.value_and_gradient(w, tb, 0.3)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=2e-4)

    def test_offsets_respected(self, rng):
        batch, d = random_problem(rng)
        batch = batch._replace(
            offsets=jnp.asarray(rng.normal(size=batch.offsets.shape).astype(np.float32))
        )
        obj = GLMObjective(LOGISTIC, d)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        tobj = TiledGLMObjective(LOGISTIC, d, interpret=True, mxu="highest")
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v0, g0 = obj.value_and_gradient(w, batch, 0.0)
        v1, g1 = tobj.value_and_gradient(w, tb, 0.0)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=2e-4)

    def test_hessian_vector(self, rng):
        batch, obj, tobj, tb, d = self._pair(rng)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        u = jnp.asarray(rng.normal(size=d).astype(np.float32))
        hv0 = obj.hessian_vector(w, u, batch, 0.2)
        hv1 = tobj.hessian_vector(w, u, tb, 0.2)
        np.testing.assert_allclose(np.asarray(hv1), np.asarray(hv0), atol=2e-4)

    def test_hessian_diagonal(self, rng):
        batch, obj, tobj, tb, d = self._pair(rng)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        h0 = obj.hessian_diagonal(w, batch, 0.1)
        h1 = tobj.hessian_diagonal(w, tb, 0.1)
        np.testing.assert_allclose(np.asarray(h1), np.asarray(h0), atol=2e-4)

    def test_linear_loss_and_duplicates(self, rng):
        # duplicate (row, feature) entries must sum, matching the ELL path
        batch = make_sparse_batch(
            [([0, 0, 2], [1.0, 2.0, -1.0]), ([1, 2], [0.5, 0.5])],
            [1.0, 0.0],
        )
        d = 3
        obj = GLMObjective(LINEAR, d)
        tb = tiled_batch_from_sparse(batch, d, params=TileParams(4, 4, 8))
        tobj = TiledGLMObjective(LINEAR, d, interpret=True, mxu="highest")
        w = jnp.asarray([0.3, -0.2, 0.9], jnp.float32)
        v0, g0 = obj.value_and_gradient(w, batch, 0.0)
        v1, g1 = tobj.value_and_gradient(w, tb, 0.0)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=1e-5)

    def test_multi_window_dims(self, rng):
        # dimensions spanning several windows on both axes
        batch, d = random_problem(rng, n=200, d=500, k=10)
        obj = GLMObjective(LOGISTIC, d)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        assert tb.num_feat_blocks >= 8 and tb.num_row_blocks >= 4
        tobj = TiledGLMObjective(LOGISTIC, d, interpret=True, mxu="highest")
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v0, g0 = obj.value_and_gradient(w, batch, 0.05)
        v1, g1 = tobj.value_and_gradient(w, tb, 0.05)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=3e-4)


class TestNormalizationParity:
    def test_normalized_matches_scatter_objective(self, rng):
        from photon_ml_tpu.ops.normalization import NormalizationContext

        batch, d = random_problem(rng)
        ctx = NormalizationContext(
            factor=jnp.asarray(rng.uniform(0.5, 2.0, d).astype(np.float32)),
            shift=jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.1),
        )
        obj = GLMObjective(LOGISTIC, d, ctx)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        tobj = TiledGLMObjective(LOGISTIC, d, norm=ctx, interpret=True, mxu="highest")
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        u = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v0, g0 = obj.value_and_gradient(w, batch, 0.2)
        v1, g1 = tobj.value_and_gradient(w, tb, 0.2)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=3e-4)
        hv0 = obj.hessian_vector(w, u, batch, 0.2)
        hv1 = tobj.hessian_vector(w, u, tb, 0.2)
        np.testing.assert_allclose(np.asarray(hv1), np.asarray(hv0), atol=3e-4)
        hd0 = obj.hessian_diagonal(w, batch, 0.1)
        hd1 = tobj.hessian_diagonal(w, tb, 0.1)
        np.testing.assert_allclose(np.asarray(hd1), np.asarray(hd0), atol=3e-4)


class TestJitArgument:
    def test_batch_passes_through_jit(self, rng):
        """The batch must be a pytree jit ARGUMENT (not a baked constant):
        at ads scale the schedule is hundreds of MB and constant-folding it
        into the executable breaks compilation."""
        import jax

        batch, d = random_problem(rng)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        tobj = TiledGLMObjective(LOGISTIC, d, interpret=True, mxu="highest")
        obj = GLMObjective(LOGISTIC, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))

        fn = jax.jit(tobj.value_and_gradient)
        v1, g1 = fn(w, tb, 0.1)
        v0, g0 = obj.value_and_gradient(w, batch, 0.1)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=2e-4)


class TestBf16x2Precision:
    def test_fast_path_within_tolerance(self, rng):
        """Default bf16x2 MXU mode: ~1e-5 relative error vs exact math."""
        batch, d = random_problem(rng)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        fast = TiledGLMObjective(LOGISTIC, d, interpret=True)
        obj = GLMObjective(LOGISTIC, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v0, g0 = obj.value_and_gradient(w, batch, 0.1)
        v1, g1 = fast.value_and_gradient(w, tb, 0.1)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-4)
        scale = float(np.max(np.abs(np.asarray(g0)))) + 1e-9
        np.testing.assert_allclose(
            np.asarray(g1) / scale, np.asarray(g0) / scale, atol=1e-4
        )


class TestMxuPackedOneHot:
    def test_mxu_onehot_bit_identical_to_compare(self, rng):
        """The MXU-packed positional expansion (squared-distance matmul +
        relu, the round-3 'pack the one-hot build onto the MXU' lever)
        must produce EXACT 0/1 one-hots — every mxu variant's output is
        bit-identical to the iota-compare build."""
        batch, d = random_problem(rng)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        for mxu in ("highest", "bf16x2", "bf16x2w"):
            a = TiledGLMObjective(
                LOGISTIC, d, interpret=True, mxu=mxu, onehot="compare"
            )
            b = TiledGLMObjective(
                LOGISTIC, d, interpret=True, mxu=mxu, onehot="mxu"
            )
            va, ga = a.value_and_gradient(w, tb, 0.1)
            vb, gb = b.value_and_gradient(w, tb, 0.1)
            assert float(va) == float(vb), mxu
            np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))

    def test_unknown_onehot_rejected(self):
        with pytest.raises(ValueError, match="onehot"):
            TiledGLMObjective(LOGISTIC, 8, onehot="typo")


class TestEmptyWindows:
    def test_empty_feature_window_zero_grad(self, rng):
        """A feature window with NO entries must yield exactly-zero gradient
        (on TPU the output buffer is uninitialized unless the schedule
        names every block — regression test for the missing-init bug)."""
        win = PARAMS.window
        d = 3 * win  # three feature windows; the middle one stays empty
        rows_list, labels = [], []
        for _ in range(40):
            lo = rng.choice(win - 1, size=2, replace=False)
            hi = rng.choice(win - 1, size=2, replace=False) + 2 * win
            ix = lo.tolist() + hi.tolist()
            vs = rng.normal(size=4).tolist()
            labels.append(float(rng.uniform() > 0.5))
            rows_list.append((ix, vs))
        batch = make_sparse_batch(rows_list, labels)
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        tobj = TiledGLMObjective(LOGISTIC, d, interpret=True, mxu="highest")
        obj = GLMObjective(LOGISTIC, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v1, g1 = tobj.value_and_gradient(w, tb, 0.0)
        v0, g0 = obj.value_and_gradient(w, batch, 0.0)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=2e-4)
        # middle window: identically zero
        assert np.all(np.asarray(g1[win : 2 * win]) == 0.0)

    def test_all_entries_dropped(self, rng):
        """Weight-0 rows drop every entry; the schedule must still cover
        all output blocks instead of crashing on an empty entry set."""
        batch = make_sparse_batch(
            [([0, 1], [1.0, 2.0]), ([2], [3.0])],
            [1.0, 0.0],
            weights=np.zeros(2),
        )
        d = 5
        tb = tiled_batch_from_sparse(batch, d, params=TileParams(4, 4, 8))
        tobj = TiledGLMObjective(LOGISTIC, d, interpret=True, mxu="highest")
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v, g = tobj.value_and_gradient(w, tb, 0.0)
        assert float(v) == 0.0
        assert np.all(np.asarray(g) == 0.0)


class TestSpill:
    """Spill-to-scatter hybrid (TileParams.spill_cap): tile remainders
    route around the kernel through _Schedule.apply_spill; the combined
    result must stay exact against the scatter objective."""

    def _spilly(self, rng, cap=8):
        batch, d = random_problem(rng, n=160, d=90, k=5)
        params = TileParams(s_hi=8, s_lo=8, chunk=32, spill_cap=cap)
        tb = tiled_batch_from_sparse(batch, d, params=params)
        return batch, tb, d

    def test_spills_present_and_exact(self, rng):
        batch, tb, d = self._spilly(rng)
        assert int(np.count_nonzero(tb.z_sched.spill_vals)) > 0
        assert int(np.count_nonzero(tb.g_sched.spill_vals)) > 0
        obj = GLMObjective(LOGISTIC, d)
        tobj = TiledGLMObjective(LOGISTIC, d, interpret=True, mxu="highest")
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v0, g0 = obj.value_and_gradient(w, batch, 0.2)
        v1, g1 = tobj.value_and_gradient(w, tb, 0.2)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(tobj.hessian_diagonal(w, tb, 0.1)),
            np.asarray(obj.hessian_diagonal(w, batch, 0.1)),
            atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(tobj.hessian_vector(w, w * 0.5, tb, 0.1)),
            np.asarray(obj.hessian_vector(w, w * 0.5, batch, 0.1)),
            atol=2e-4,
        )

    def test_spill_reduces_steps(self, rng):
        batch, d = random_problem(rng, n=160, d=90, k=5)
        no_spill = tiled_batch_from_sparse(
            batch, d, params=TileParams(s_hi=8, s_lo=8, chunk=32, spill_cap=0)
        )
        spill = tiled_batch_from_sparse(
            batch, d, params=TileParams(s_hi=8, s_lo=8, chunk=32, spill_cap=8)
        )
        assert spill.z_sched.num_steps < no_spill.z_sched.num_steps
        assert int(np.count_nonzero(no_spill.z_sched.spill_vals)) == 0

    def test_native_matches_numpy_builder(self, rng):
        from photon_ml_tpu.ops import tiled_sparse as ts

        if not ts._tile_lib():
            pytest.skip("native tile builder unavailable")
        n, d, nnz = 400, 260, 5000
        rows = rng.integers(0, n, nnz).astype(np.int64)
        feats = rng.integers(0, d, nnz).astype(np.int64)
        vals = rng.normal(size=nnz).astype(np.float32)
        params = TileParams(s_hi=8, s_lo=8, chunk=32, spill_cap=8)
        win = params.window
        nob = (n + win - 1) // win
        for by_feat, blocks in ((False, nob), (True, (d + win - 1) // win)):
            native = ts._build_schedule_native(
                rows, feats, vals, params=params,
                sort_by_feature_block=by_feat, num_out_blocks=blocks,
            )
            assert native is not None
            saved = ts._tile_lib_handle
            ts._tile_lib_handle = False
            try:
                pyb = ts._build_schedule_np(
                    rows, feats, vals, params=params,
                    sort_by_feature_block=by_feat, num_out_blocks=blocks,
                )
            finally:
                ts._tile_lib_handle = saved
            assert int(np.count_nonzero(native[8])) > 0  # spill exercised
            for a, b in zip(native, pyb):
                np.testing.assert_array_equal(a, b)


class TestWideMxuVariant:
    """mxu="bf16x2w": fused full-width matmuls must match the scatter
    oracle and the two-matmul bf16x2 variant."""

    def test_matches_oracle_and_bf16x2(self, rng):
        from photon_ml_tpu.data.batch import SparseBatch

        n, k, d = 96, 6, 130
        indices = rng.integers(0, d, size=(n, k)).astype(np.int32)
        values = rng.normal(size=(n, k)).astype(np.float32)
        labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
        batch = SparseBatch(
            indices=jnp.asarray(indices), values=jnp.asarray(values),
            labels=jnp.asarray(labels), offsets=jnp.zeros(n),
            weights=jnp.ones(n),
        )
        tb = tiled_batch_from_sparse(batch, d, params=PARAMS)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.5)
        oobj = GLMObjective(LOGISTIC, d)
        v0, g0 = oobj.value_and_gradient(w, batch, 0.1)
        for mxu in ("bf16x2", "bf16x2w"):
            tobj = TiledGLMObjective(LOGISTIC, d, interpret=True, mxu=mxu)
            v1, g1 = tobj.value_and_gradient(w, tb, 0.1)
            assert abs(float(v1 - v0)) / abs(float(v0)) < 1e-4
            assert (
                float(jnp.linalg.norm(g1 - g0) / jnp.linalg.norm(g0)) < 1e-4
            )
            hv0 = oobj.hessian_vector(w, w * 0.3, batch, 0.1)
            hv1 = tobj.hessian_vector(w, w * 0.3, tb, 0.1)
            assert (
                float(jnp.linalg.norm(hv1 - hv0) / jnp.linalg.norm(hv0))
                < 1e-4
            )
