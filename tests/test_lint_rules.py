"""photon-lint rule corpus: one positive and one negative fixture per
rule (tests/lint_fixtures/), suppression semantics, the PL001 allow-site
seam audit, baseline round-tripping, and the CLI surface."""

import json
import os
import subprocess
import sys

import pytest

from photon_ml_tpu.lint import (
    analyze_paths,
    analyze_source,
    apply_baseline,
    load_baseline,
    write_baseline,
)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")


def _report(relpath):
    report = analyze_paths([os.path.join(FIXTURES, relpath)])
    assert not report.errors, report.errors
    return report


def _violations(relpath):
    return _report(relpath).violations


def _rules(violations):
    return [v.rule for v in violations]


class TestRuleFixtures:
    def test_pl001_positive(self):
        vs = _violations("pl001_pos.py")
        assert _rules(vs) == ["PL001"] * 7  # one per seeded sync

    def test_pl001_negative(self):
        assert _violations("pl001_neg.py") == []

    def test_pl002_positive(self):
        vs = _violations("pl002_pos.py")
        assert _rules(vs) == ["PL002"] * 5

    def test_pl002_negative(self):
        assert _violations("pl002_neg.py") == []

    def test_pl003_positive(self):
        vs = _violations("pl003_pos.py")
        assert _rules(vs) == ["PL003"] * 5

    def test_pl003_negative(self):
        assert _violations("pl003_neg.py") == []

    def test_pl004_positive(self):
        vs = _violations("io/pl004_pos.py")
        assert _rules(vs) == ["PL004"] * 3

    def test_pl004_negative(self):
        assert _violations("io/pl004_neg.py") == []

    def test_pl004_out_of_scope(self):
        # same factory calls, but not under io// game streaming
        assert _violations("pl004_out_of_scope.py") == []

    def test_pl006_positive(self):
        vs = _violations("pl006_pos.py")
        # two torn artifact writes + two swallowed IO failures
        assert _rules(vs) == ["PL006"] * 4, vs
        assert {v.line for v in vs} == {8, 13, 22, 31}

    def test_pl006_negative(self):
        # atomic helpers, explicit temp+os.replace, io_call-routed
        # swallows, read/append modes, and teardown scopes all pass
        assert _violations("pl006_neg.py") == []

    def test_pl005_positive(self):
        vs = _violations("pl005_pos.py")
        assert _rules(vs) == ["PL005"] * 2

    def test_pl005_negative(self):
        assert _violations("pl005_neg.py") == []

    def test_pl007_positive(self):
        vs = _violations("serving/pl007_pos.py")
        # untimed Condition.wait, Event.wait, Future.result
        assert _rules(vs) == ["PL007"] * 3, vs

    def test_pl007_negative(self):
        # timed waits, done-callback result(timeout=0), local helpers
        assert _violations("serving/pl007_neg.py") == []

    def test_pl007_out_of_scope(self):
        # the same untimed waits outside serving/ are not flagged
        assert _violations("pl007_out_of_scope.py") == []

    def test_pl008_positive(self):
        vs = _violations("pl008_pos.py")
        # bare write + bare read of an inferred-guard attr, atomic
        # augwrite, declared-guard miss, thread-shared flag (both
        # sides), lambda thread target, escaped shared local,
        # lock-expected helper called bare
        assert _rules(vs) == ["PL008"] * 9, vs

    def test_pl008_negative(self):
        # locked accesses, atomic publishes, queue/event handoffs,
        # guarded escapes, lock-expected helpers called under the lock
        assert _violations("pl008_neg.py") == []

    def test_pl009_positive(self):
        vs = _violations("pl009_pos.py")
        # ONE inversion cycle, reported at BOTH edge sites
        assert _rules(vs) == ["PL009"] * 2, vs
        assert all("cycle" in v.message for v in vs)

    def test_pl009_negative(self):
        assert _violations("pl009_neg.py") == []

    def test_pl010_positive(self):
        vs = _violations("pl010_pos.py")
        # callback under a cond-backed lock, blocking call under it,
        # notify without the lock, check-then-act across a release,
        # foreign lock-taking method under the wait lock
        assert _rules(vs) == ["PL010"] * 5, vs

    def test_pl010_negative(self):
        # callbacks after release, notify under the condition, outer
        # lock spanning a read-then-write protocol
        assert _violations("pl010_neg.py") == []

    def test_pl011_positive(self):
        vs = _violations("pl011_pos.py")
        # P() literal, collective literal, typo'd axis, axis-param
        # default, BoolOp fallback
        assert _rules(vs) == ["PL011"] * 5, vs
        assert sum("unknown mesh axis" in v.message for v in vs) == 1

    def test_pl011_negative(self):
        # constants everywhere; matching declarations incl. multi-axis
        # spec tokens and a variadic tail
        assert _violations("pl011_neg.py") == []

    def test_pl011_contract_positive(self):
        vs = _violations("photon_ml_tpu/spmd_contract_pos.py")
        # undeclared entry point, typo'd declared axis (+ the axis it
        # therefore misses), in= spec drift
        assert _rules(vs) == ["PL011"] * 4, vs
        msgs = " | ".join(v.message for v in vs)
        assert "no '# photon: sharding(...)' declaration" in msgs
        assert "unknown axis 'entiy'" in msgs
        assert "does not name" in msgs
        assert "drifted from the code" in msgs

    def test_pl012_positive(self):
        vs = _violations("photon_ml_tpu/pl012_pos.py")
        # undeclared to_global, device_get through the counted seam,
        # np.asarray of a .sharded_bank attribute
        assert _rules(vs) == ["PL012"] * 3, vs

    def test_pl012_negative(self):
        # declared export/checkpoint scopes + scalar readbacks +
        # non-bank numpy stay silent
        assert _violations("photon_ml_tpu/pl012_neg.py") == []

    def test_pl013_positive(self):
        vs = _violations("pl013_pos.py")
        # unreduced P() output, psum over an axis the specs never shard
        assert _rules(vs) == ["PL013"] * 2, vs

    def test_pl013_negative(self):
        # complete reductions, psum-through-helper one hop, unknown
        # calls unflagged
        assert _violations("pl013_neg.py") == []

    def test_pl014_positive(self):
        vs = _violations("pl014_pos.py")
        # direct use-after-donate + donation through a builder-made
        # callable
        assert _rules(vs) == ["PL014"] * 2, vs

    def test_pl014_negative(self):
        # rebind swap (incl. in-loop), conditional donate tuple via a
        # local helper, defensive copy, non-donated positions
        assert _violations("pl014_neg.py") == []

    def test_pl015_positive(self):
        vs = _violations("pl015_pos.py")
        # set payload into atomic_write_json, listdir into json.dumps,
        # set-algebra into json.dumps, for-over-set in a writer scope
        assert _rules(vs) == ["PL015"] * 4, vs

    def test_pl015_negative(self):
        # same shapes sorted(); order-erasing reductions; iterating a
        # set in a scope that writes nothing
        assert _violations("pl015_neg.py") == []

    def test_pl016_positive(self):
        vs = _violations("pl016_pos.py")
        # pid artifact, two clock payloads, id() cache get + store,
        # hostname return, one stale + one reasonless declaration
        assert _rules(vs) == ["PL016"] * 8, vs
        msgs = " | ".join(v.message for v in vs)
        assert "stale entropy declaration" in msgs
        assert "without a reason" in msgs
        # the declaration grammar is a CLAIM, not a suppression
        assert all(not v.suppressable for v in vs)

    def test_pl016_negative(self):
        # declared sites (site-line and def-line), durations, clock
        # comparisons, hash()-keying, content-derived seeds
        assert _violations("pl016_neg.py") == []

    def test_pl017_positive(self):
        vs = _violations("pl017_pos.py")
        # sum/math.fsum/np.sum over unordered iterables
        assert _rules(vs) == ["PL017"] * 3, vs

    def test_pl017_negative(self):
        assert _violations("pl017_neg.py") == []

    def test_pl018_positive(self):
        vs = _violations("pl018_pos")
        # duplicate wire value, orphan encoder/decoder/dispatch,
        # unmapped WireError kind (the fixture package has no tests
        # tree, so the corpus leg correctly stays out of scope)
        assert _rules(vs) == ["PL018"] * 5, vs
        msgs = " | ".join(v.message for v in vs)
        assert "reuses wire value" in msgs
        assert "no encoder" in msgs
        assert "no decoder branch" in msgs
        assert "never dispatched" in msgs
        assert "'oversized' has no frontend mapping" in msgs
        assert all(not v.suppressable for v in vs)

    def test_pl018_negative(self):
        assert _violations("pl018_neg") == []


class TestSuppression:
    def test_allow_comments_suppress(self):
        report = _report("suppressed.py")
        # every seeded violation is allowed except the one whose comment
        # names the WRONG rule
        assert len(report.violations) == 1
        v = report.violations[0]
        assert v.rule == "PL001"
        assert "wrong_rule_does_not_suppress" in "".join(
            open(os.path.join(FIXTURES, "suppressed.py"))
            .read()
            .splitlines()[v.line - 3: v.line]
        )
        assert len(report.allow_sites) == 5

    def test_both_id_and_slug_work(self):
        src = (
            "import jax\n"
            "def f(t):\n"
            "    return jax.device_get(t)  # photon: allow(PL001)\n"
            "def g(t):\n"
            "    return jax.device_get(t)  "
            "# photon: allow(hidden-host-sync)\n"
        )
        assert analyze_source("scratch.py", src).violations == []

    def test_standalone_comment_covers_next_line(self):
        src = (
            "import jax\n"
            "def f(t):\n"
            "    # photon: allow(hidden-host-sync)\n"
            "    return jax.device_get(t)\n"
        )
        assert analyze_source("scratch.py", src).violations == []

    def test_unrelated_comment_does_not_suppress(self):
        src = (
            "import jax\n"
            "def f(t):\n"
            "    return jax.device_get(t)  # plain comment\n"
        )
        assert len(analyze_source("scratch.py", src).violations) == 1

    def test_package_rule_violations_are_suppressable(self):
        # allow() works on the concurrency pass too (id or slug)
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._flag = False\n"
            "    def w(self):\n"
            "        with self._lock:\n"
            "            self._flag = True\n"
            "    def r(self):\n"
            "        return self._flag\n"
        )
        assert len(analyze_source("scratch.py", src).violations) == 1
        allowed = src.replace(
            "        return self._flag\n",
            "        return self._flag  "
            "# photon: allow(unguarded-shared-state)\n",
        )
        assert analyze_source("scratch.py", allowed).violations == []

    def test_guarded_by_is_a_declaration_not_a_suppression(self):
        # annotating an attr does NOT silence it — the declaration is
        # enforced (naming a non-lock is itself a violation)
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0  # photon: guarded-by(_nope)\n"
            "    def w(self):\n"
            "        self._x = 1\n"
        )
        vs = analyze_source("scratch.py", src).violations
        assert len(vs) == 1 and "not a lock" in vs[0].message


class TestSeamAudit:
    def test_unaccounted_allow_site_is_a_violation(self):
        vs = _violations("photon_ml_tpu/audit_pos.py")
        assert len(vs) == 1
        assert vs[0].rule == "PL001"
        assert "unaccounted" in vs[0].message
        assert not vs[0].suppressable

    def test_accounted_allow_sites_pass(self):
        report = _report("photon_ml_tpu/audit_neg.py")
        assert report.violations == []
        assert [s.seam_ok for s in report.allow_sites] == [True, True]

    def test_audit_violation_cannot_be_suppressed(self):
        # stacking more allow comments on the rogue line changes nothing
        src = (
            "import jax\n"
            "def f(t):\n"
            "    # photon: allow(PL001)\n"
            "    return jax.device_get(t)  "
            "# photon: allow(hidden-host-sync, PL001)\n"
        )
        vs = analyze_source("photon_ml_tpu/fake.py", src).violations
        assert len(vs) == 1 and "unaccounted" in vs[0].message

    def test_audit_is_informational_outside_package(self):
        report = _report("suppressed.py")
        pl001_sites = [
            s for s in report.allow_sites
            if s.rules & {"PL001", "hidden-host-sync"}
        ]
        assert pl001_sites and all(
            s.seam_ok is False for s in pl001_sites
        )  # recorded, but no violation (checked in test above)


class TestBaseline:
    def test_round_trip(self, tmp_path):
        report = _report("pl001_pos.py")
        n = len(report.violations)
        assert n == 7
        path = str(tmp_path / "baseline.json")
        write_baseline(path, report.violations)
        fresh = _report("pl001_pos.py")
        apply_baseline(fresh, load_baseline(path))
        assert fresh.violations == []
        assert fresh.baselined == n
        assert fresh.unused_baseline == []

    def test_deleting_one_entry_resurfaces_the_violation(self, tmp_path):
        report = _report("pl001_pos.py")
        path = str(tmp_path / "baseline.json")
        write_baseline(path, report.violations)
        data = json.load(open(path))
        removed = data["entries"].pop(0)
        json.dump(data, open(path, "w"))
        fresh = _report("pl001_pos.py")
        apply_baseline(fresh, load_baseline(path))
        assert len(fresh.violations) == removed["count"]
        assert fresh.violations[0].snippet == removed["snippet"]

    def test_unused_entries_are_reported(self, tmp_path):
        report = _report("pl001_pos.py")
        path = str(tmp_path / "baseline.json")
        write_baseline(path, report.violations)
        fresh = _report("pl001_neg.py")  # clean file, stale baseline
        apply_baseline(fresh, load_baseline(path))
        assert fresh.violations == []
        assert len(fresh.unused_baseline) == len(
            json.load(open(path))["entries"]
        )

    def test_version_mismatch_rejected(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        json.dump({"version": 999, "entries": []}, open(path, "w"))
        with pytest.raises(ValueError):
            load_baseline(path)

    def test_pl008_pl010_round_trip(self, tmp_path):
        # the concurrency rules baseline like any other rule...
        for fixture in ("pl008_pos.py", "pl010_pos.py"):
            report = _report(fixture)
            assert report.violations
            path = str(tmp_path / f"b-{fixture}.json")
            write_baseline(path, report.violations)
            fresh = _report(fixture)
            apply_baseline(fresh, load_baseline(path))
            assert fresh.violations == []
            assert fresh.unused_baseline == []

    def test_pl009_refuses_to_baseline(self, tmp_path):
        # ...except PL009: a lock inversion is never grandfathered
        from photon_ml_tpu.lint import BaselineRefused

        report = _report("pl009_pos.py")
        assert report.violations
        path = str(tmp_path / "b.json")
        with pytest.raises(BaselineRefused):
            write_baseline(path, report.violations)
        assert not os.path.exists(path), "refusal must not write"

    def test_hand_edited_pl009_baseline_entry_rejected(self, tmp_path):
        path = str(tmp_path / "b.json")
        json.dump(
            {"version": 1, "entries": [{
                "file": "x.py", "rule": "PL009",
                "snippet": "with a:", "count": 1,
            }]},
            open(path, "w"),
        )
        with pytest.raises(ValueError, match="never baseline-able"):
            load_baseline(path)

    def test_pl011_pl013_pl014_round_trip(self, tmp_path):
        # the SPMD rules baseline like any other rule...
        for fixture in ("pl011_pos.py", "pl013_pos.py", "pl014_pos.py"):
            report = _report(fixture)
            assert report.violations
            path = str(tmp_path / f"b-{fixture}.json")
            write_baseline(path, report.violations)
            fresh = _report(fixture)
            apply_baseline(fresh, load_baseline(path))
            assert fresh.violations == []
            assert fresh.unused_baseline == []

    def test_pl012_refuses_to_baseline(self, tmp_path):
        # ...except PL012: a sharded-bank host gather is never
        # grandfathered (the PL009 discipline)
        from photon_ml_tpu.lint import BaselineRefused

        report = _report("photon_ml_tpu/pl012_pos.py")
        assert report.violations
        path = str(tmp_path / "b.json")
        with pytest.raises(BaselineRefused, match="shard-local"):
            write_baseline(path, report.violations)
        assert not os.path.exists(path), "refusal must not write"

    def test_hand_edited_pl012_baseline_entry_rejected(self, tmp_path):
        path = str(tmp_path / "b.json")
        json.dump(
            {"version": 1, "entries": [{
                "file": "x.py", "rule": "PL012",
                "snippet": "bank.to_global()", "count": 1,
            }]},
            open(path, "w"),
        )
        with pytest.raises(ValueError, match="never baseline-able"):
            load_baseline(path)

    def test_pl015_pl017_round_trip(self, tmp_path):
        # the order rules baseline like any other rule...
        for fixture in ("pl015_pos.py", "pl017_pos.py"):
            report = _report(fixture)
            assert report.violations
            path = str(tmp_path / f"b-{fixture}.json")
            write_baseline(path, report.violations)
            fresh = _report(fixture)
            apply_baseline(fresh, load_baseline(path))
            assert fresh.violations == []
            assert fresh.unused_baseline == []

    def test_pl016_refuses_to_baseline(self, tmp_path):
        # ...except PL016: entropy in artifacts is declared or fixed,
        # never grandfathered (the PL009/PL012 discipline)
        from photon_ml_tpu.lint import BaselineRefused

        report = _report("pl016_pos.py")
        assert report.violations
        path = str(tmp_path / "b.json")
        with pytest.raises(BaselineRefused, match="entropy"):
            write_baseline(path, report.violations)
        assert not os.path.exists(path), "refusal must not write"

    def test_pl018_refuses_to_baseline(self, tmp_path):
        # ...and PL018: a half-wired message type is a protocol hole,
        # not debt to inherit
        from photon_ml_tpu.lint import BaselineRefused

        report = _report("pl018_pos")
        assert report.violations
        path = str(tmp_path / "b.json")
        with pytest.raises(BaselineRefused, match="wire"):
            write_baseline(path, report.violations)
        assert not os.path.exists(path), "refusal must not write"

    def test_hand_edited_pl016_pl018_baseline_entries_rejected(
        self, tmp_path
    ):
        for rule, snippet in (
            ("PL016", "os.getpid()"),
            ("PL018", "MSG_ORPHAN = 0x03"),
        ):
            path = str(tmp_path / f"b-{rule}.json")
            json.dump(
                {"version": 1, "entries": [{
                    "file": "x.py", "rule": rule,
                    "snippet": snippet, "count": 1,
                }]},
                open(path, "w"),
            )
            with pytest.raises(ValueError, match="never baseline-able"):
                load_baseline(path)


class TestCLI:
    def _run(self, *args, cwd=None):
        return subprocess.run(
            [sys.executable, "-m", "photon_ml_tpu.lint", *args],
            capture_output=True, text=True, cwd=cwd,
        )

    def test_violations_exit_1_with_locations(self):
        r = self._run(
            os.path.join(FIXTURES, "pl001_pos.py"), "--no-baseline"
        )
        assert r.returncode == 1
        # clickable file:line:col locations
        assert "pl001_pos.py:9:" in r.stdout
        assert "PL001" in r.stdout

    def test_clean_exit_0(self):
        r = self._run(
            os.path.join(FIXTURES, "pl001_neg.py"), "--no-baseline"
        )
        assert r.returncode == 0

    def test_json_mode(self):
        r = self._run(
            os.path.join(FIXTURES, "suppressed.py"), "--no-baseline",
            "--json",
        )
        data = json.loads(r.stdout)
        assert r.returncode == 1
        assert data["files_checked"] == 1
        assert len(data["violations"]) == 1
        assert data["violations"][0]["rule"] == "PL001"
        # allow-sites are listed for tooling, seam audit included
        assert len(data["allow_sites"]) == 5
        assert any("seam_ok" in s for s in data["allow_sites"])

    def test_syntax_error_exits_2(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def f(:\n")
        r = self._run(str(bad), "--no-baseline")
        assert r.returncode == 2

    def test_list_rules(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        for rid in ("PL001", "PL002", "PL003", "PL004", "PL005",
                    "PL006", "PL007", "PL008", "PL009", "PL010",
                    "PL011", "PL012", "PL013", "PL014", "PL015",
                    "PL016", "PL017", "PL018"):
            assert rid in r.stdout
        assert "unguarded-shared-state" in r.stdout
        assert "lock-order-inversion" in r.stdout
        assert "atomicity-hygiene" in r.stdout
        assert "mesh-axis-discipline" in r.stdout
        assert "sharded-bank-host-gather" in r.stdout
        assert "reduction-completeness" in r.stdout
        assert "donation-hygiene" in r.stdout
        assert "unordered-iteration-to-artifact" in r.stdout
        assert "ambient-entropy-in-artifact" in r.stdout
        assert "float-accumulation-order" in r.stdout
        assert "wire-contract-completeness" in r.stdout

    def test_json_covers_concurrency_rules(self):
        r = self._run(
            os.path.join(FIXTURES, "pl008_pos.py"), "--no-baseline",
            "--json",
        )
        data = json.loads(r.stdout)
        assert r.returncode == 1
        assert {v["rule"] for v in data["violations"]} == {"PL008"}
        assert len(data["violations"]) == 9

    def test_no_concurrency_flag_skips_the_package_pass(self):
        r = self._run(
            os.path.join(FIXTURES, "pl008_pos.py"), "--no-baseline",
            "--no-concurrency",
        )
        assert r.returncode == 0, r.stdout

    def test_write_baseline_refuses_pl009_with_exit_2(self, tmp_path):
        target = str(tmp_path / "b.json")
        r = self._run(
            os.path.join(FIXTURES, "pl009_pos.py"),
            "--write-baseline", "--baseline", target,
        )
        assert r.returncode == 2
        assert "never" in r.stderr.lower() or "cannot" in r.stderr.lower()
        assert not os.path.exists(target)

    def test_write_baseline_refuses_pl012_with_exit_2(self, tmp_path):
        target = str(tmp_path / "b.json")
        r = self._run(
            os.path.join(FIXTURES, "photon_ml_tpu", "pl012_pos.py"),
            "--write-baseline", "--baseline", target,
        )
        assert r.returncode == 2
        assert "shard-local" in r.stderr
        assert not os.path.exists(target)

    def test_no_spmd_flag_skips_the_spmd_pass(self):
        r = self._run(
            os.path.join(FIXTURES, "pl011_pos.py"), "--no-baseline",
            "--no-spmd",
        )
        assert r.returncode == 0, r.stdout
        # ...and the concurrency pass still runs independently
        r = self._run(
            os.path.join(FIXTURES, "pl008_pos.py"), "--no-baseline",
            "--no-spmd",
        )
        assert r.returncode == 1, r.stdout

    def test_no_determinism_flag_skips_the_pass(self):
        r = self._run(
            os.path.join(FIXTURES, "pl015_pos.py"), "--no-baseline",
            "--no-determinism",
        )
        assert r.returncode == 0, r.stdout
        # ...and the concurrency pass still runs independently
        r = self._run(
            os.path.join(FIXTURES, "pl008_pos.py"), "--no-baseline",
            "--no-determinism",
        )
        assert r.returncode == 1, r.stdout

    def test_write_baseline_refuses_pl016_with_exit_2(self, tmp_path):
        target = str(tmp_path / "b.json")
        r = self._run(
            os.path.join(FIXTURES, "pl016_pos.py"),
            "--write-baseline", "--baseline", target,
        )
        assert r.returncode == 2
        assert "entropy" in r.stderr
        assert not os.path.exists(target)

    def test_write_baseline_refuses_pl018_with_exit_2(self, tmp_path):
        target = str(tmp_path / "b.json")
        r = self._run(
            os.path.join(FIXTURES, "pl018_pos"),
            "--write-baseline", "--baseline", target,
        )
        assert r.returncode == 2
        assert "wire" in r.stderr
        assert not os.path.exists(target)

    def test_json_carries_wire_contract_inventory(self):
        r = self._run(
            os.path.join(FIXTURES, "pl018_pos"), "--no-baseline",
            "--json",
        )
        data = json.loads(r.stdout)
        assert r.returncode == 1
        assert {v["rule"] for v in data["violations"]} == {"PL018"}
        contract = data["wire_contract"]
        names = {m["name"] for m in contract["messages"]}
        assert names == {"MSG_JSON", "MSG_SCORE", "MSG_DUP", "MSG_ORPHAN"}
        orphan = [
            m for m in contract["messages"] if m["name"] == "MSG_ORPHAN"
        ][0]
        assert orphan["encoders"] == []
        assert orphan["decoded"] is False
        assert orphan["dispatch"] == []
        assert contract["error_kinds"] == {
            "malformed": True, "oversized": False,
        }

    def test_json_carries_entropy_declaration_table(self):
        r = self._run(
            os.path.join(FIXTURES, "pl016_neg.py"), "--no-baseline",
            "--json",
        )
        data = json.loads(r.stdout)
        assert r.returncode == 0
        decls = data["entropy_declarations"]
        assert decls, "declared sites must ride the json report"
        reasons = {d["reason"] for d in decls}
        assert any("discovery artifact" in x for x in reasons)
        assert any("lease identity" in x for x in reasons)

    def test_json_omits_determinism_tables_when_opted_out(self):
        r = self._run(
            os.path.join(FIXTURES, "pl016_neg.py"), "--no-baseline",
            "--json", "--no-determinism",
        )
        data = json.loads(r.stdout)
        assert r.returncode == 0
        assert "wire_contract" not in data
        assert "entropy_declarations" not in data

    def test_json_covers_spmd_rules_and_contract_table(self):
        r = self._run(
            os.path.join(FIXTURES, "photon_ml_tpu",
                         "spmd_contract_pos.py"),
            "--no-baseline", "--json",
        )
        data = json.loads(r.stdout)
        assert r.returncode == 1
        assert {v["rule"] for v in data["violations"]} == {"PL011"}
        assert len(data["violations"]) == 4
        # the sharding-contract table rides the json report
        assert "sharding_contracts" in data
        entries = data["sharding_contracts"]
        assert len(entries) == 3
        assert {e["entry"] for e in entries} == {
            "undeclared_entry.vg", "typo_axis_declared.vg",
            "spec_drift_declared.vg",
        }
        undeclared = [
            e for e in entries if e["entry"] == "undeclared_entry.vg"
        ][0]
        assert undeclared["declared"] == "NO"

    def test_json_lists_export_scopes(self):
        r = self._run(
            os.path.join(FIXTURES, "photon_ml_tpu", "pl012_neg.py"),
            "--no-baseline", "--json",
        )
        data = json.loads(r.stdout)
        assert r.returncode == 0
        scopes = {s["scope"] for s in data["export_scopes"]}
        assert scopes == {"export_model", "checkpoint_bank"}

    def test_sharding_md_check_detects_drift(self, tmp_path):
        md = tmp_path / "SHARDING.md"
        fixture = os.path.join(FIXTURES, "photon_ml_tpu",
                               "spmd_contract_pos.py")
        r = self._run(fixture, "--write-sharding-md", str(md))
        assert r.returncode == 0, r.stdout + r.stderr
        r = self._run(fixture, "--check-sharding-md", str(md))
        assert r.returncode == 0, r.stdout + r.stderr
        md.write_text(md.read_text().replace(
            "undeclared_entry.vg", "renamed_entry.vg"
        ))
        r = self._run(fixture, "--check-sharding-md", str(md))
        assert r.returncode == 1
        assert "stale" in r.stderr


class TestShardingDeclarations:
    def test_declaration_is_a_contract_not_a_suppression(self):
        # annotating an entry point does NOT silence PL011 — a wrong
        # declaration is itself the violation
        src = (
            "from functools import partial\n"
            "import jax\n"
            "from jax import lax, shard_map\n"
            "from jax.sharding import PartitionSpec as P\n"
            "DATA_AXIS = 'data'\n"
            "def f(mesh):\n"
            "    # photon: sharding(axes=[model], in=[r,data], out=[r])\n"
            "    @partial(shard_map, mesh=mesh,\n"
            "             in_specs=(P(), P(DATA_AXIS)), out_specs=P(),\n"
            "             check_vma=False)\n"
            "    def vg(w, batch):\n"
            "        return lax.psum(batch.sum() * w.sum(), DATA_AXIS)\n"
            "    return jax.jit(vg)\n"
        )
        from photon_ml_tpu.lint import analyze_source

        vs = analyze_source("photon_ml_tpu/fake.py", src).violations
        assert vs and all(v.rule == "PL011" for v in vs)

    def test_parse_grammar(self):
        from photon_ml_tpu.lint.spmd import parse_sharding_decl

        d = parse_sharding_decl(
            1, "axes=[data,model], in=[r,data+model,*], out=?, "
               "donates=[0,2]"
        )
        assert d.axes == ["data", "model"]
        assert d.in_specs == ["r", "data+model", "*"]
        assert d.out_specs is None
        assert d.donates == [0, 2]
        assert not d.export and not d.errors
        e = parse_sharding_decl(1, "export")
        assert e.export and e.axes is None and not e.errors
        bad = parse_sharding_decl(1, "axes=?, frobnicate=[1]")
        assert bad.errors

    def test_spec_matching_semantics(self):
        from photon_ml_tpu.lint.spmd import specs_match

        assert specs_match(["r", "data"], ["r", "data"])
        assert specs_match(["r", "?"], ["r", "entity"])
        assert specs_match(["entity", "*"], ["entity"] * 6)
        assert not specs_match(["data"], ["r", "data"])
        assert not specs_match(["r", "data"], ["r", "model"])
        assert not specs_match(["r", "data", "r"], ["r", "data"])
