"""Reliability layer (round 11): deterministic fault injection, retrying
IO, quarantine, atomic artifacts, and crash-safe resume.

The crash tests use the fault plan's ``KILL`` kind — SIGKILL delivered
to the process itself at an exact seam crossing — so "kill -9 mid-stage"
is a deterministic, replayable event, not a sleep-and-hope race. The
resume contract under test: restart with the SAME args and the final
artifacts are BITWISE equal to an uninterrupted run (Avro containers
included — their sync markers are schema-derived, not random).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu.reliability import (
    FaultPlan,
    GridCheckpointer,
    InjectedCorruption,
    SeamFailure,
    StreamingCDCheckpointer,
    atomic_write_json,
    atomic_writer,
    ensure_run_manifest,
    install_plan,
    io_call,
    quarantine_artifact,
    read_manifest,
    reset_fault_stats,
    reset_retry_stats,
    retry_stats,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_reliability(monkeypatch):
    monkeypatch.setenv("PHOTON_RETRY_BASE_S", "0.001")
    reset_fault_stats()
    reset_retry_stats()
    yield
    reset_fault_stats()
    reset_retry_stats()


# ---------------------------------------------------------------------------
# fault plans
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_parse_forms(self):
        plan = FaultPlan.parse(
            "chunk_read:3:EIO,ckpt_save:1:ENOSPC:2,spill_write:2:eio:once,"
            "cache_load:1:CORRUPT,spill_read:4:EIO:*"
        )
        assert len(plan.entries) == 5
        e = {x.seam: x for x in plan.entries}
        assert e["chunk_read"].nth == 3 and e["chunk_read"].times == 1
        assert e["ckpt_save"].times == 2
        assert e["spill_write"].times == 1
        assert e["cache_load"].error == "CORRUPT"
        assert e["spill_read"].times == -1  # poisoned: every call from 4

    @pytest.mark.parametrize("bad", [
        "not_a_seam:1:EIO",        # unknown seam
        "chunk_read:0:EIO",        # nth < 1
        "chunk_read:1:EFOO",       # unknown error
        "chunk_read:1",            # too few fields
        "chunk_read:1:EIO:0",      # times < 1
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)

    def test_deterministic_by_occurrence(self):
        """The same plan over the same call sequence injects at exactly
        the same crossings — replayability is the whole point."""
        for _ in range(2):
            plan = FaultPlan.parse("chunk_read:3:EIO:2")
            outcomes = []
            for _ in range(6):
                try:
                    plan.check("chunk_read")
                    outcomes.append("ok")
                except OSError:
                    outcomes.append("EIO")
            assert outcomes == ["ok", "ok", "EIO", "EIO", "ok", "ok"]

    def test_env_plan_single_transient_retries(self, monkeypatch):
        monkeypatch.setenv("PHOTON_FAULT_PLAN", "spill_read:1:EIO")
        reset_fault_stats()  # force re-resolution from the env var
        assert io_call("spill_read", lambda: 7, detail="x") == 7
        assert retry_stats()["retries"]["spill_read"] == 1

    def test_serving_model_load_is_a_registered_seam(self):
        """ISSUE 7: the serving bank-load/swap seam is a first-class
        member of the fault surface — plans parse it (the dot is part
        of the name, not plan syntax) and it carries its own retry
        budget instead of the default policy."""
        from photon_ml_tpu.reliability import SEAMS, policy_for
        from photon_ml_tpu.reliability.retry import _POLICIES

        assert "serving.model_load" in SEAMS
        assert "serving.model_load" in _POLICIES
        assert policy_for("serving.model_load").max_attempts == 3
        plan = FaultPlan.parse("serving.model_load:2:CORRUPT")
        assert plan.entries[0].seam == "serving.model_load"
        assert not plan.entries[0].fires_at(1)
        assert plan.entries[0].fires_at(2)

    def test_serving_request_path_seams_are_registered(self):
        """ISSUE 8: the front-end read and dispatch seams join the
        fault surface. Reads never retry (a broken socket is the
        client's named error, not the service's backoff loop);
        dispatch is idempotent pure compute, so transients retry on a
        fast budget."""
        from photon_ml_tpu.reliability import SEAMS, policy_for
        from photon_ml_tpu.reliability.retry import _POLICIES

        for seam in ("serving.frontend.read", "serving.dispatch"):
            assert seam in SEAMS
            assert seam in _POLICIES
            plan = FaultPlan.parse(f"{seam}:3:EIO")
            assert plan.entries[0].seam == seam
        assert policy_for("serving.frontend.read").max_attempts == 1
        assert policy_for("serving.dispatch").max_attempts == 3


# ---------------------------------------------------------------------------
# io_call / retry / quarantine
# ---------------------------------------------------------------------------


class TestIoCall:
    def test_transient_fault_retries_to_success(self):
        install_plan("chunk_read:1:EIO")
        calls = []
        assert io_call("chunk_read", lambda: calls.append(1) or 42) == 42
        assert len(calls) == 1  # first ATTEMPT failed at inject, not in fn
        r = retry_stats()
        assert r["retries"]["chunk_read"] == 1
        assert r["giveups"] == {}

    def test_budget_exhaustion_names_the_artifact(self):
        install_plan("spill_write:1:EIO:*")
        with pytest.raises(SeamFailure) as ei:
            io_call("spill_write", lambda: None, detail="chunks/ix.bin[3]")
        assert "spill_write" in str(ei.value)
        assert "chunks/ix.bin[3]" in str(ei.value)
        assert retry_stats()["giveups"]["spill_write"] == 1

    def test_corruption_is_not_retried(self):
        install_plan("cache_load:1:CORRUPT")
        with pytest.raises(InjectedCorruption):
            io_call("cache_load", lambda: None, detail="artifact")
        assert retry_stats()["retries"] == {}  # straight through

    def test_real_oserror_retries_without_a_plan(self):
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise OSError("transient")
            return "done"

        assert io_call("spill_read", flaky) == "done"
        assert len(attempts) == 3

    def test_quarantine_accounts_and_renames(self, tmp_path):
        p = tmp_path / "poison.npy"
        p.write_bytes(b"bad")
        dst = quarantine_artifact(str(p), "cache_load")
        assert dst.endswith(".corrupt") and os.path.exists(dst)
        assert not p.exists()
        # collision gets a numbered suffix, never overwrites evidence
        p.write_bytes(b"bad again")
        dst2 = quarantine_artifact(str(p), "cache_load")
        assert dst2.endswith(".corrupt-1")
        r = retry_stats()
        assert r["quarantined"]["cache_load"] == 2
        assert dst in r["quarantined_artifacts"]


# ---------------------------------------------------------------------------
# atomic artifacts + manifests
# ---------------------------------------------------------------------------


class TestAtomicArtifacts:
    def test_atomic_writer_publishes_complete_files(self, tmp_path):
        p = tmp_path / "nested" / "out.txt"
        with atomic_writer(str(p)) as f:
            f.write("payload")
        assert p.read_text() == "payload"

    def test_atomic_writer_error_leaves_nothing(self, tmp_path):
        p = tmp_path / "out.json"
        p.write_text("old")
        with pytest.raises(RuntimeError):
            with atomic_writer(str(p)) as f:
                f.write("half-writ")
                raise RuntimeError("crash mid-write")
        assert p.read_text() == "old"  # old content intact, no temp left
        assert os.listdir(tmp_path) == ["out.json"]

    def test_atomic_write_json(self, tmp_path):
        p = tmp_path / "m.json"
        atomic_write_json(str(p), {"k": [1, 2]})
        assert json.load(open(p)) == {"k": [1, 2]}

    def test_run_manifest_guard(self, tmp_path):
        d = str(tmp_path / "ck")
        ensure_run_manifest(d, {"grid": [1.0, 0.1]}, kind="glm-grid")
        ensure_run_manifest(d, {"grid": [1.0, 0.1]}, kind="glm-grid")  # ok
        with pytest.raises(ValueError, match="different run configuration"):
            ensure_run_manifest(d, {"grid": [9.0]}, kind="glm-grid")
        with pytest.raises(ValueError, match="different run configuration"):
            ensure_run_manifest(d, {"grid": [1.0, 0.1]}, kind="other")

    def test_torn_manifest_quarantined_not_trusted(self, tmp_path):
        d = str(tmp_path)
        (tmp_path / "manifest.json").write_text('{"kind": "ga')  # torn
        assert read_manifest(d) is None
        assert any(
            f.startswith("manifest.json.corrupt") for f in os.listdir(d)
        )


# ---------------------------------------------------------------------------
# schedule-cache quarantine (satellite 2)
# ---------------------------------------------------------------------------


class TestScheduleCacheQuarantine:
    def _store_one(self, cache_dir):
        from photon_ml_tpu.ops import schedule_cache as sc

        arrays = [
            np.arange(8, dtype=np.int32) + i
            for i in range(len(sc.SCHEDULE_ARRAY_NAMES))
        ]
        assert sc.store_schedule(cache_dir, "k" * 32, arrays)
        return sc, arrays

    def test_corrupt_artifact_quarantined_and_rebuilt(self, tmp_path):
        cache = str(tmp_path)
        sc, arrays = self._store_one(cache)
        sc.reset_stats()
        d = sc._artifact_dir(cache, "k" * 32)
        # damage one array file's tail -> spot digest mismatch
        with open(os.path.join(d, "step_out.npy"), "r+b") as f:
            f.seek(0, 2)
            f.truncate(max(f.tell() - 4, 0))
        assert sc.load_schedule(cache, "k" * 32) is None
        s = sc.stats()
        assert s.corrupt == 1 and s.quarantined == 1 and s.misses == 1
        assert os.path.isdir(d + ".corrupt")
        assert not os.path.isdir(d)
        # the poison is OUT of the way: a re-store succeeds and loads
        assert sc.store_schedule(cache, "k" * 32, arrays)
        assert sc.load_schedule(cache, "k" * 32) is not None

    def test_transient_load_fault_retries(self, tmp_path):
        cache = str(tmp_path)
        sc, _ = self._store_one(cache)
        sc.reset_stats()
        install_plan("cache_load:1:EIO")
        out = sc.load_schedule(cache, "k" * 32)
        assert out is not None  # retried through the transient fault
        assert sc.stats().hits == 1
        assert retry_stats()["retries"]["cache_load"] == 1


# ---------------------------------------------------------------------------
# checkpointers
# ---------------------------------------------------------------------------


class TestGridCheckpointer:
    def test_round_trip(self, tmp_path):
        g = GridCheckpointer(str(tmp_path / "g"), {"grid": [1.0]})
        g.save(
            1.0,
            warm_means=np.arange(4, dtype=np.float32),
            model_means=np.arange(4, dtype=np.float32) * 2,
            model_variances=np.ones(4, np.float32),
            result_arrays={
                "value": np.float32(3.5),
                "iterations": np.int32(7),
            },
        )
        assert g.has(1.0) and not g.has(0.1)
        snap = g.load(1.0)
        np.testing.assert_array_equal(snap["warm_means"], np.arange(4))
        assert snap["result"]["iterations"] == 7

    def test_snapshot_without_marker_is_invisible(self, tmp_path):
        """The commit protocol: npz first, JSON marker second. A crash
        between the two (npz on disk, no marker) must read as 'not
        checkpointed' — resume re-solves that λ instead of trusting an
        unconfirmed snapshot."""
        g = GridCheckpointer(str(tmp_path / "g"), {"grid": [1.0]})
        g.save(
            1.0, warm_means=np.zeros(2), model_means=np.zeros(2),
            model_variances=None, result_arrays={},
        )
        os.unlink(g._base(1.0) + ".json")
        assert not g.has(1.0)
        assert g.load(1.0) is None


class TestStreamingCDCheckpointer:
    def test_round_trip_and_pruning(self, tmp_path):
        cd = StreamingCDCheckpointer(str(tmp_path), max_to_keep=2)
        for it in range(1, 4):
            cd.save(
                it,
                {"global": np.full(3, float(it)), "per-user": np.eye(2)},
                {"global": None, "per-user": np.ones((2, 2))},
                {"objective": [float(i) for i in range(it)]},
            )
        assert cd.steps() == [2, 3]
        states, variances, hist = cd.load(3)
        np.testing.assert_array_equal(states["global"], [3.0, 3.0, 3.0])
        assert variances["global"] is None
        np.testing.assert_array_equal(variances["per-user"], np.ones((2, 2)))
        assert hist["objective"] == [0.0, 1.0, 2.0]


# ---------------------------------------------------------------------------
# interrupted stage pass resumes from completed chunks (tentpole 3)
# ---------------------------------------------------------------------------


def _write_game_files(base, seed=0):
    sys.path.insert(0, os.path.join(REPO, "dev-scripts"))
    import chaos_matrix

    chaos_matrix.gen_game_data(base, seed=seed)


class TestStageResume:
    def test_interrupted_stage_resumes_bitwise(self, tmp_path):
        """Stage with a poisoned spill_write (budget exhausts mid-pass),
        then resume with no plan: the resumed store's chunk files must
        be bitwise identical to an uninterrupted store's, WITHOUT
        re-consuming the already-staged records."""
        from photon_ml_tpu.game.config import FeatureShardConfiguration
        from photon_ml_tpu.game.streaming import (
            scan_game_stream,
            stage_game_stream,
        )

        data = str(tmp_path / "data")
        _write_game_files(data)
        shards = [
            FeatureShardConfiguration("globalShard", ["features"]),
            FeatureShardConfiguration("userShard", ["userFeatures"]),
        ]
        imaps, eidx, stats = scan_game_stream([data], shards, ["userId"])

        def stage(persist, plan):
            install_plan(plan)
            try:
                return stage_game_stream(
                    [data], shards, ["userId"], imaps, eidx, stats,
                    rows_per_chunk=64, persist_dir=persist,
                )
            finally:
                install_plan(None)

        clean = str(tmp_path / "clean")
        stage(clean, None)
        # interrupted arm: every spill_write from crossing 30 on fails
        # -> SeamFailure mid-stage, some chunks already committed
        broken = str(tmp_path / "broken")
        with pytest.raises(SeamFailure):
            stage(broken, "spill_write:30:EIO:*")
        m = read_manifest(broken)
        assert 0 < m["chunks"] < json.load(
            open(os.path.join(clean, "manifest.json"))
        )["chunks"] + 1
        resumed_store, _ = stage(broken, None)
        assert resumed_store.staged
        clean_manifest = read_manifest(clean)
        broken_manifest = read_manifest(broken)
        for key in ("chunks", "real_rows"):
            assert broken_manifest[key] == clean_manifest[key]
        for fn in sorted(os.listdir(clean)):
            if fn.endswith(".bin"):
                a = open(os.path.join(clean, fn), "rb").read()
                b = open(os.path.join(broken, fn), "rb").read()
                assert a == b, f"{fn} differs after resume"


# ---------------------------------------------------------------------------
# λ-grid checkpoint/preemption wiring (training.py)
# ---------------------------------------------------------------------------


class TestGridCheckpointWiring:
    def _fit(self, tmp_path, **kw):
        import jax.numpy as jnp

        from photon_ml_tpu.data.batch import SparseBatch
        from photon_ml_tpu.task import TaskType
        from photon_ml_tpu.training import train_generalized_linear_model

        # fixed seed: every _fit in a test must see the SAME batch, or
        # the bitwise comparisons compare different problems
        rng = np.random.default_rng(42)
        n, d, k = 400, 20, 4
        ix = rng.integers(0, d, size=(n, k)).astype(np.int32)
        vs = rng.normal(size=(n, k)).astype(np.float32)
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        batch = SparseBatch(
            jnp.asarray(ix), jnp.asarray(vs), jnp.asarray(y),
            jnp.zeros(n), jnp.ones(n),
        )
        return train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d,
            regularization_weights=[10.0, 1.0, 0.1], max_iter=10, **kw
        )

    def test_snapshots_reload_bitwise(self, tmp_path):
        """A sweep under a GridCheckpointer snapshots every λ, and a
        second sweep over the same checkpointer loads them all without
        re-solving — bitwise equal to a checkpointer-less reference fit
        (the mid-path variant runs as a subprocess kill -9 test below)."""
        from photon_ml_tpu.reliability import GridCheckpointer

        models_ref, _ = self._fit(tmp_path)
        ck = GridCheckpointer(str(tmp_path / "g"), {"v": 1})
        m_a, r_a = self._fit(tmp_path, grid_checkpointer=ck)
        assert sorted(m_a) == [0.1, 1.0, 10.0]
        # a fresh sweep over the SAME checkpointer loads every λ without
        # solving, bitwise equal to the reference fit
        m_b, r_b = self._fit(tmp_path, grid_checkpointer=ck)
        for lam in m_a:
            np.testing.assert_array_equal(
                np.asarray(m_a[lam].means), np.asarray(m_b[lam].means)
            )
            np.testing.assert_array_equal(
                np.asarray(models_ref[lam].means),
                np.asarray(m_b[lam].means),
            )
            assert int(r_b[lam].iterations) == int(r_a[lam].iterations)

    def test_preemption_stops_at_lambda_boundary(self, tmp_path):
        from photon_ml_tpu.reliability import GridCheckpointer

        class Guard:
            def __init__(self):
                self.requested = False

        guard = Guard()
        ck = GridCheckpointer(str(tmp_path / "g"), {"v": 1})
        # pre-request: the sweep must stop BEFORE solving anything new
        # once λs already loaded from snapshots are exhausted
        guard.requested = True
        models, results = self._fit(
            tmp_path, grid_checkpointer=ck, preemption_guard=guard
        )
        assert models == {} and results == {}


# ---------------------------------------------------------------------------
# kill -9 resume, end-to-end through the drivers (satellite 3)
# ---------------------------------------------------------------------------


def _run_driver(args, *, expect_kill=False, env=None, timeout=560):
    e = {**os.environ, "JAX_PLATFORMS": "cpu",
         "PHOTON_RETRY_BASE_S": "0.001", **(env or {})}
    r = subprocess.run(
        args, cwd=REPO, env=e, capture_output=True, text=True,
        timeout=timeout,
    )
    if expect_kill:
        assert r.returncode == -9, (
            f"expected SIGKILL, got rc={r.returncode}\n{r.stderr[-2000:]}"
        )
    else:
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-3000:]
    return r


def _tree_bytes(root):
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            p = os.path.join(dirpath, fn)
            out[os.path.relpath(p, root)] = open(p, "rb").read()
    return out


def _assert_tree_equal(a, b, label):
    ta, tb = _tree_bytes(a), _tree_bytes(b)
    assert ta.keys() == tb.keys(), (label, ta.keys() ^ tb.keys())
    diff = [k for k in ta if ta[k] != tb[k]]
    assert not diff, f"{label}: files differ after resume: {diff}"


class TestKillMinusNineResume:
    def _glm_args(self, train, out, ckpt, plan=None):
        args = [
            sys.executable, "-m", "photon_ml_tpu.cli.glm_driver",
            "--training-data-directory", train,
            "--output-directory", out,
            "--task", "LOGISTIC_REGRESSION",
            "--regularization-weights", "10,1,0.1",
            "--grid-mode", "sequential",
            "--num-iterations", "15",
            "--delete-output-dirs-if-exist", "true",
        ]
        if ckpt:
            args += ["--checkpoint-dir", ckpt]
        if plan:
            args += ["--fault-plan", plan]
        return args

    def test_glm_lambda_grid_killed_mid_path_resumes_bitwise(
        self, tmp_path
    ):
        """kill -9 during the 2nd λ's snapshot write: λ1 is committed,
        λ2 is not. Restart with the same args; the resumed sweep loads
        λ1, re-solves λ2 from λ1's snapshotted warm means, and the final
        model artifacts are bitwise equal to an uninterrupted run."""
        sys.path.insert(0, os.path.join(REPO, "dev-scripts"))
        import chaos_matrix

        train = str(tmp_path / "train")
        chaos_matrix.gen_glm_data(train)
        clean_out = str(tmp_path / "out-clean")
        kill_out = str(tmp_path / "out-kill")
        ckpt = str(tmp_path / "ckpt")
        _run_driver(self._glm_args(train, clean_out, None))
        # ckpt_save crossings: 1 = run manifest, 2-3 = λ1 npz+marker,
        # 4 = λ2 npz -> SIGKILL lands mid-λ2-snapshot
        _run_driver(
            self._glm_args(train, kill_out, ckpt, plan="ckpt_save:4:KILL"),
            expect_kill=True,
        )
        assert os.path.isdir(ckpt), "no snapshots before the kill"
        assert any(f.endswith(".json") and f.startswith("lambda-")
                   for f in os.listdir(ckpt)), os.listdir(ckpt)
        _run_driver(self._glm_args(train, kill_out, ckpt))
        _assert_tree_equal(
            os.path.join(clean_out, "models"),
            os.path.join(kill_out, "models"), "GLM models",
        )
        _assert_tree_equal(
            os.path.join(clean_out, "models-text"),
            os.path.join(kill_out, "models-text"), "GLM models-text",
        )

    def _game_args(self, train, out, ckpt, plan=None):
        args = [
            sys.executable, "-m", "photon_ml_tpu.cli.game_training_driver",
            "--train-input-dirs", train,
            "--output-dir", out,
            "--task-type", "LOGISTIC_REGRESSION",
            "--feature-shard-id-to-feature-section-keys-map",
            "globalShard:features|userShard:userFeatures",
            "--fixed-effect-data-configurations", "global:globalShard,1",
            "--fixed-effect-optimization-configurations",
            "global:20,1e-6,0.5,1,TRON,L2",
            "--random-effect-data-configurations",
            "per-user:userId,userShard,1,none,none,none,identity",
            "--random-effect-optimization-configurations",
            "per-user:20,1e-6,1.0,1,LBFGS,L2",
            "--num-iterations", "2",
            "--streaming", "true",
            # ~8 KiB budget -> ~56-row chunks over 450 records, so the
            # stage pass spans ~9 chunks and a kill can land INSIDE it
            "--stream-memory-budget", str(8 << 10),
            "--checkpoint-dir", ckpt,
            "--delete-output-dir-if-exists", "true",
        ]
        if plan:
            args += ["--fault-plan", plan]
        return args

    def test_game_streaming_killed_mid_stage_resumes_bitwise(
        self, tmp_path
    ):
        """kill -9 inside the stage pass (a spill_write crossing early
        in chunk staging): the restart resumes staging from the
        manifest's completed chunks and the final best-model is bitwise
        equal to an uninterrupted run."""
        train = str(tmp_path / "train")
        _write_game_files(train)
        clean_out = str(tmp_path / "out-clean")
        kill_out = str(tmp_path / "out-kill")
        _run_driver(
            self._game_args(train, clean_out, str(tmp_path / "ck-clean"))
        )
        ckpt = str(tmp_path / "ck-kill")
        _run_driver(
            self._game_args(
                train, kill_out, ckpt, plan="spill_write:12:KILL"
            ),
            expect_kill=True,
        )
        combo_dir = os.path.join(ckpt, sorted(os.listdir(ckpt))[0])
        stage_manifest = read_manifest(os.path.join(combo_dir, "stage-train"))
        assert stage_manifest is not None and not stage_manifest.get(
            "staged"
        ), stage_manifest
        _run_driver(self._game_args(train, kill_out, ckpt))
        _assert_tree_equal(
            os.path.join(clean_out, "best-model"),
            os.path.join(kill_out, "best-model"),
            "GAME best-model (killed mid-stage)",
        )

    def test_game_streaming_killed_mid_cd_resumes_bitwise(self, tmp_path):
        """kill -9 after at least one CD iteration checkpointed (a
        spill_read crossing deep into the CD loop): the restart skips
        the stage pass (manifest), restores the latest CD snapshot,
        rebuilds scores from states, finishes the remaining iterations
        — final model bitwise equal to the uninterrupted run."""
        train = str(tmp_path / "train")
        _write_game_files(train)
        clean_out = str(tmp_path / "out-clean")
        kill_out = str(tmp_path / "out-kill")
        _run_driver(
            self._game_args(train, clean_out, str(tmp_path / "ck-clean"))
        )
        ckpt = str(tmp_path / "ck-kill")
        # crossing budget (counted on a clean run of this exact config):
        # fill pass = 9 spill_reads, each CD iteration ~260, whole run
        # ~529 — crossing 300 lands inside ITERATION 2, after iteration
        # 1's snapshot committed
        _run_driver(
            self._game_args(
                train, kill_out, ckpt, plan="spill_read:300:KILL"
            ),
            expect_kill=True,
        )
        combo_dir = os.path.join(ckpt, sorted(os.listdir(ckpt))[0])
        cd_dir = os.path.join(combo_dir, "cd")
        assert os.path.isdir(cd_dir) and any(
            f.endswith(".json") for f in os.listdir(cd_dir)
        ), "kill landed before the first CD snapshot — adjust the crossing"
        stage_manifest = read_manifest(
            os.path.join(combo_dir, "stage-train")
        )
        assert stage_manifest.get("staged"), "stage should have completed"
        _run_driver(self._game_args(train, kill_out, ckpt))
        _assert_tree_equal(
            os.path.join(clean_out, "best-model"),
            os.path.join(kill_out, "best-model"),
            "GAME best-model (killed mid-CD)",
        )
