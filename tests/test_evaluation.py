"""Evaluation tests: metrics vs brute-force oracles (incl. sklearn-free
pairwise AUC), sharded evaluators, evaluator-type parsing, model selection.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.evaluation import (
    Evaluator,
    EvaluatorType,
    area_under_precision_recall_curve,
    area_under_roc_curve,
    f1_score,
    mean_pointwise_loss,
    precision_at_k,
    root_mean_squared_error,
    select_best_model,
    sharded_auc,
    sharded_precision_at_k,
)
from photon_ml_tpu.ops.losses import LOGISTIC


def brute_force_auc(scores, labels, weights):
    pos = [(s, w) for s, y, w in zip(scores, labels, weights) if y > 0.5 and w > 0]
    neg = [(s, w) for s, y, w in zip(scores, labels, weights) if y <= 0.5 and w > 0]
    num = 0.0
    for sp, wp in pos:
        for sn, wn in neg:
            if sp > sn:
                num += wp * wn
            elif sp == sn:
                num += 0.5 * wp * wn
    den = sum(w for _, w in pos) * sum(w for _, w in neg)
    return num / den


class TestAUC:
    def test_matches_brute_force(self, rng):
        n = 64
        scores = rng.normal(size=n).astype(np.float32)
        labels = (rng.uniform(size=n) > 0.4).astype(np.float32)
        weights = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
        got = float(area_under_roc_curve(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights)))
        assert got == pytest.approx(brute_force_auc(scores, labels, weights), abs=1e-5)

    def test_ties(self, rng):
        scores = np.array([1.0, 1.0, 0.0, 0.0], np.float32)
        labels = np.array([1.0, 0.0, 1.0, 0.0], np.float32)
        weights = np.ones(4, np.float32)
        got = float(area_under_roc_curve(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights)))
        assert got == pytest.approx(brute_force_auc(scores, labels, weights), abs=1e-6)

    def test_perfect_separation(self):
        scores = jnp.array([3.0, 2.0, -1.0, -2.0])
        labels = jnp.array([1.0, 1.0, 0.0, 0.0])
        w = jnp.ones(4)
        assert float(area_under_roc_curve(scores, labels, w)) == pytest.approx(1.0)

    def test_padding_rows_ignored(self, rng):
        scores = np.array([1.0, -1.0, 99.0], np.float32)
        labels = np.array([1.0, 0.0, 0.0], np.float32)
        weights = np.array([1.0, 1.0, 0.0], np.float32)  # last row = padding
        got = float(area_under_roc_curve(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights)))
        assert got == pytest.approx(1.0)


class TestOtherMetrics:
    def test_rmse(self, rng):
        p = rng.normal(size=32).astype(np.float32)
        y = rng.normal(size=32).astype(np.float32)
        w = rng.uniform(0.1, 2.0, size=32).astype(np.float32)
        expect = np.sqrt(np.sum(w * (p - y) ** 2) / np.sum(w))
        got = float(root_mean_squared_error(jnp.asarray(p), jnp.asarray(y), jnp.asarray(w)))
        assert got == pytest.approx(expect, rel=1e-5)

    def test_mean_logistic_loss(self):
        z = jnp.array([0.0, 2.0])
        y = jnp.array([1.0, 0.0])
        w = jnp.array([1.0, 1.0])
        expect = (np.log(2.0) + np.log1p(np.exp(2.0))) / 2.0
        assert float(mean_pointwise_loss(LOGISTIC, z, y, w)) == pytest.approx(expect, rel=1e-5)

    def test_precision_at_k(self):
        scores = jnp.array([5.0, 4.0, 3.0, 2.0, 1.0])
        labels = jnp.array([1.0, 0.0, 1.0, 1.0, 1.0])
        w = jnp.ones(5)
        assert float(precision_at_k(3, scores, labels, w)) == pytest.approx(2 / 3)

    def test_precision_at_k_ignores_padding(self):
        scores = jnp.array([5.0, 4.0, 3.0])
        labels = jnp.array([1.0, 1.0, 1.0])
        w = jnp.array([1.0, 0.0, 1.0])
        assert float(precision_at_k(2, scores, labels, w)) == pytest.approx(1.0)

    def test_aupr_sane(self, rng):
        n = 128
        scores = rng.normal(size=n).astype(np.float32)
        labels = (scores + 0.5 * rng.normal(size=n) > 0).astype(np.float32)
        w = np.ones(n, np.float32)
        aupr = float(area_under_precision_recall_curve(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(w)))
        base = labels.mean()
        assert base < aupr <= 1.0

    def test_f1(self):
        pred = jnp.array([1.0, 1.0, 0.0, 0.0])
        lab = jnp.array([1.0, 0.0, 1.0, 0.0])
        w = jnp.ones(4)
        assert float(f1_score(pred, lab, w)) == pytest.approx(0.5)


class TestSharded:
    def test_sharded_auc_mean_of_groups(self, rng):
        # Two groups with known local AUCs.
        gids = np.array([0, 0, 0, 0, 1, 1, 1, 1], np.int32)
        scores = np.array([3, 2, 1, 0, 3, 2, 1, 0], np.float32)
        labels = np.array([1, 1, 0, 0, 0, 1, 0, 1], np.float32)
        w = np.ones(8, np.float32)
        local0 = brute_force_auc(scores[:4], labels[:4], w[:4])
        local1 = brute_force_auc(scores[4:], labels[4:], w[4:])
        got = float(sharded_auc(jnp.asarray(gids), jnp.asarray(scores),
                                jnp.asarray(labels), jnp.asarray(w), 2))
        assert got == pytest.approx((local0 + local1) / 2, abs=1e-5)

    def test_sharded_auc_skips_single_class_groups(self):
        gids = jnp.array([0, 0, 1, 1], jnp.int32)
        scores = jnp.array([2.0, 1.0, 2.0, 1.0])
        labels = jnp.array([1.0, 0.0, 1.0, 1.0])  # group 1 all-positive
        w = jnp.ones(4)
        assert float(sharded_auc(gids, scores, labels, w, 2)) == pytest.approx(1.0)

    def test_sharded_auc_random_matches_per_group_brute_force(self, rng):
        n, G = 96, 7
        gids = rng.integers(0, G, size=n).astype(np.int32)
        scores = rng.normal(size=n).astype(np.float32)
        labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
        w = rng.uniform(0.5, 1.5, size=n).astype(np.float32)
        locals_ = []
        for g in range(G):
            m = gids == g
            if m.sum() and labels[m].max() > 0.5 and labels[m].min() <= 0.5:
                locals_.append(brute_force_auc(scores[m], labels[m], w[m]))
        got = float(sharded_auc(jnp.asarray(gids), jnp.asarray(scores),
                                jnp.asarray(labels), jnp.asarray(w), G))
        assert got == pytest.approx(np.mean(locals_), abs=1e-5)

    def test_sharded_precision_at_k(self):
        gids = jnp.array([0, 0, 0, 1, 1, 1], jnp.int32)
        scores = jnp.array([3.0, 2.0, 1.0, 3.0, 2.0, 1.0])
        labels = jnp.array([1.0, 0.0, 1.0, 1.0, 1.0, 0.0])
        w = jnp.ones(6)
        got = float(sharded_precision_at_k(2, gids, scores, labels, w, 2))
        assert got == pytest.approx((0.5 + 1.0) / 2)


class TestEvaluatorTypes:
    def test_parse_simple(self):
        assert EvaluatorType.parse("AUC").name == "AUC"
        assert EvaluatorType.parse("rmse").name == "RMSE"
        assert EvaluatorType.parse("LOGISTIC_LOSS").name == "LOGISTIC_LOSS"

    def test_parse_sharded(self):
        et = EvaluatorType.parse("precision@5:queryId")
        assert et.name == "PRECISION_AT_K" and et.k == 5 and et.id_type == "queryId"
        et2 = EvaluatorType.parse("AUC:documentId")
        assert et2.name == "AUC" and et2.id_type == "documentId"

    def test_render_roundtrip(self):
        for s in ["AUC", "RMSE", "PRECISION@5:queryId", "AUC:docId"]:
            assert EvaluatorType.parse(EvaluatorType.parse(s).render()) == EvaluatorType.parse(s)

    def test_parse_unknown(self):
        with pytest.raises(ValueError):
            EvaluatorType.parse("NDCG")

    def test_direction(self):
        assert EvaluatorType.parse("AUC").better_than(0.9, 0.8)
        assert EvaluatorType.parse("RMSE").better_than(0.1, 0.2)

    def test_evaluator_dispatch(self, rng):
        n = 32
        scores = jnp.asarray(rng.normal(size=n).astype(np.float32))
        labels = jnp.asarray((rng.uniform(size=n) > 0.5).astype(np.float32))
        w = jnp.ones(n)
        ev = Evaluator(EvaluatorType.parse("AUC"))
        assert 0.0 <= float(ev.evaluate(scores, labels, w)) <= 1.0
        with pytest.raises(ValueError):
            Evaluator(EvaluatorType.parse("AUC:qid")).evaluate(scores, labels, w)


class TestModelSelection:
    def test_select_best(self):
        models = {0.1: "m1", 1.0: "m2", 10.0: "m3"}
        metrics = {"m1": 0.7, "m2": 0.9, "m3": 0.8}
        lam, model, metric = select_best_model(
            models, lambda m: metrics[m], maximize=True
        )
        assert (lam, model, metric) == (1.0, "m2", 0.9)
        lam, model, metric = select_best_model(
            models, lambda m: metrics[m], maximize=False
        )
        assert model == "m1"
