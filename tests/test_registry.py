"""ISSUE 10: model registry + safe continuous retraining.

- publish protocol: atomic visibility (COMMIT marker), KILL at every
  ``registry.publish`` seam crossing leaves committed-or-nothing and
  resume republishes bitwise; idempotent republish; refusal/quarantine/
  retention semantics; single-writer lease contention + dead-owner
  takeover.
- drift-safe warm-start alignment matrix: vocab grow/shrink, entity
  churn (prior-mean init), no-drift bitwise pins (GLM vector + RE bank).
- per-partition stats cache: hit/miss counters, appended partitions
  scan only the new files, identical scan results, corruption
  quarantine.
- validation gates: pass/fail verdicts per gate, round-trip through the
  manifest, refused candidates never loadable.
- registry watcher: promotion on publish, post-swap health regression
  auto-rollback restoring the parent bank BITWISE + registry
  quarantine, frontend status lineage + operator rollback op.
- driver e2e: GLM + GAME retrain-from/publish round trips.
"""

import filecmp
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu.registry import (
    DriftReport,
    GateConfig,
    GateReport,
    ModelRegistry,
    RefusedCandidate,
    RegistryLeaseHeld,
    RollbackPolicy,
    align_coefficients,
    align_re_bank,
    cached_scan_stream,
    cached_scan_stream_with_summary,
    content_signature,
    evaluate_gates,
)
from photon_ml_tpu.registry.registry import _Lease
from photon_ml_tpu.utils.index_map import IndexMap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_model(path, payload=b"MODEL-BYTES-1"):
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "model.avro"), "wb") as f:
        f.write(payload)
    return path


@pytest.fixture()
def model_dir(tmp_path):
    return _write_model(str(tmp_path / "candidate"))


@pytest.fixture()
def registry(tmp_path):
    return ModelRegistry(str(tmp_path / "registry"))


class TestPublish:
    def test_publish_commit_and_lineage(self, registry, model_dir, tmp_path):
        info = registry.publish(
            model_dir, data_ranges={"train_dir": "d1"}
        )
        assert info.generation == 1
        assert registry.latest().generation == 1
        assert registry.latest().gate_verdict == "UNGATED"
        m2 = _write_model(str(tmp_path / "m2"), b"MODEL-BYTES-2")
        info2 = registry.publish(m2, parent=1)
        assert info2.parent == 1
        assert registry.lineage() == [2, 1]
        # manifest records the data ranges and the content signature
        assert info.manifest["data_ranges"] == {"train_dir": "d1"}
        assert info2.signature == content_signature(m2)

    def test_uncommitted_generation_is_invisible(self, registry, model_dir):
        info = registry.publish(model_dir)
        os.unlink(os.path.join(info.path, "COMMIT"))
        assert registry.latest() is None
        assert registry.list_generations() == []

    def test_republish_same_content_is_idempotent(
        self, registry, model_dir
    ):
        a = registry.publish(model_dir)
        b = registry.publish(model_dir)
        assert (a.generation, a.signature) == (b.generation, b.signature)
        assert [g.generation for g in registry.list_generations()] == [1]

    def test_refused_candidate_never_loadable(
        self, registry, model_dir, tmp_path
    ):
        registry.publish(model_dir)
        bad = _write_model(str(tmp_path / "bad"), b"BAD-MODEL")
        with pytest.raises(RefusedCandidate) as ei:
            registry.publish(
                bad, parent=1,
                gate_report={"verdict": "AUC_REGRESSION", "checks": {}},
            )
        assert ei.value.verdict == "AUC_REGRESSION"
        # the loader view is unchanged; the refusal is on record
        assert [g.generation for g in registry.list_generations()] == [1]
        refusals = registry.refused_candidates()
        assert len(refusals) == 1
        assert refusals[0]["gates"]["verdict"] == "AUC_REGRESSION"
        assert refusals[0]["signature"] == content_signature(bad)

    def test_quarantine_hides_generation_and_burns_number(
        self, registry, model_dir, tmp_path
    ):
        registry.publish(model_dir)
        m2 = _write_model(str(tmp_path / "m2"), b"G2")
        registry.publish(m2, parent=1)
        q = registry.quarantine_generation(2, reason="rollback test")
        assert q is not None and os.path.isdir(q)
        assert registry.latest().generation == 1
        with open(os.path.join(q, "quarantine.json")) as f:
            assert json.load(f)["reason"] == "rollback test"
        # the number is burned: the next publish is generation 3
        m3 = _write_model(str(tmp_path / "m3"), b"G3")
        assert registry.publish(m3, parent=1).generation == 3

    def test_gc_keeps_referenced_parents(self, registry, tmp_path):
        for i in range(5):
            m = _write_model(str(tmp_path / f"m{i}"), f"G{i}".encode())
            parent = registry.latest()
            registry.publish(
                m,
                parent=parent.generation if parent else None,
            )
        removed = registry.gc(keep=2)
        kept = [g.generation for g in registry.list_generations()]
        # newest 2 plus generation 3 (parent of 4, the oldest retained)
        assert kept == [3, 4, 5]
        assert removed == [1, 2]

    def test_missing_model_dir_fails_before_lease(self, registry):
        with pytest.raises(ValueError, match="does not exist"):
            registry.publish(str(registry.root) + "/nope")


class TestLease:
    def test_live_holder_wins_second_publisher_loses_cleanly(
        self, registry, model_dir
    ):
        registry._ensure_layout()
        holder = _Lease(registry.root)
        holder.acquire()
        try:
            with pytest.raises(RegistryLeaseHeld):
                registry.publish(model_dir)
            # the loser wrote NOTHING
            assert registry.list_generations() == []
            assert os.listdir(registry.generations_dir) == []
        finally:
            holder.release()
        # lease released: publish proceeds
        assert registry.publish(model_dir).generation == 1

    def test_dead_owner_lease_is_broken(self, registry, model_dir):
        registry._ensure_layout()
        import socket

        with open(os.path.join(registry.root, "lease.json"), "w") as f:
            json.dump(
                {
                    "pid": 2 ** 30,  # no such pid
                    "host": socket.gethostname(),
                    "token": "dead",
                },
                f,
            )
        assert registry.publish(model_dir).generation == 1

    def test_torn_lease_file_is_broken(self, registry, model_dir):
        registry._ensure_layout()
        with open(os.path.join(registry.root, "lease.json"), "w") as f:
            f.write('{"pid": 12')  # killed mid-write
        assert registry.publish(model_dir).generation == 1


_PUBLISH_HELPER = """
import sys
sys.path.insert(0, {repo!r})
from photon_ml_tpu.registry import ModelRegistry
info = ModelRegistry(sys.argv[1]).publish(
    sys.argv[2], parent=None, data_ranges={{"train_dir": "d"}}
)
print(info.generation)
"""


class TestPublishKillMatrix:
    """Fault-plan KILL at every ``registry.publish`` seam crossing: the
    loader view is committed-or-nothing, and the resumed publish is
    bitwise the uninterrupted one. (The registry imports without jax,
    so each subprocess run is sub-second.)"""

    def _publish(self, reg_dir, model, plan=None):
        env = dict(os.environ)
        env.pop("PHOTON_FAULT_PLAN", None)
        if plan:
            env["PHOTON_FAULT_PLAN"] = plan
        return subprocess.run(
            [sys.executable, "-c",
             _PUBLISH_HELPER.format(repo=REPO), reg_dir, model],
            capture_output=True, text=True, env=env, timeout=60,
        )

    def _tree_equal(self, a, b):
        for root, _dirs, files in os.walk(a):
            for f in files:
                rel = os.path.relpath(os.path.join(root, f), a)
                if not filecmp.cmp(
                    os.path.join(a, rel), os.path.join(b, rel),
                    shallow=False,
                ):
                    return False
        return True

    def test_kill_at_every_crossing_never_partial_resume_bitwise(
        self, tmp_path
    ):
        model = _write_model(str(tmp_path / "model"), b"KILL-MATRIX")
        ref = str(tmp_path / "reg-ref")
        r = self._publish(ref, model)
        assert r.returncode == 0, r.stderr
        ref_gen = os.path.join(ref, "generations", "g000001")

        saw_kill = 0
        for n in range(1, 8):
            reg_dir = str(tmp_path / f"reg-k{n}")
            r = self._publish(
                reg_dir, model, plan=f"registry.publish:{n}:KILL"
            )
            killed = r.returncode == -9
            saw_kill += int(killed)
            vis = [
                g.generation
                for g in ModelRegistry(reg_dir).list_generations()
            ]
            # committed-or-nothing: NEVER a partial generation
            assert vis in ([], [1]), (n, vis)
            if vis == [1]:
                gen = os.path.join(reg_dir, "generations", "g000001")
                assert self._tree_equal(ref_gen, gen), n
            # resume: exactly one generation, bitwise the reference
            r2 = self._publish(reg_dir, model)
            assert r2.returncode == 0, (n, r2.stderr)
            vis2 = [
                g.generation
                for g in ModelRegistry(reg_dir).list_generations()
            ]
            assert vis2 == [1], (n, vis2)
            gen = os.path.join(reg_dir, "generations", "g000001")
            assert self._tree_equal(ref_gen, gen), n
            if not killed:
                break  # past the last crossing: plan never fired
        # the plan actually killed at the real crossings (>= 4:
        # lease-acquire, stage, rename, commit)
        assert saw_kill >= 4

    def test_kill_mid_stage_leaves_adoptable_or_invisible_state(
        self, tmp_path
    ):
        """KILL at the commit crossing specifically: the renamed
        directory exists WITHOUT a marker (invisible), and the resumed
        publish ADOPTS it (marker-only commit)."""
        model = _write_model(str(tmp_path / "model"), b"ADOPT-ME")
        reg_dir = str(tmp_path / "reg")
        r = self._publish(
            reg_dir, model, plan="registry.publish:4:KILL"
        )
        assert r.returncode == -9
        gen_dir = os.path.join(reg_dir, "generations", "g000001")
        assert os.path.isdir(gen_dir)  # renamed...
        assert not os.path.exists(os.path.join(gen_dir, "COMMIT"))
        assert ModelRegistry(reg_dir).list_generations() == []
        model_sig = content_signature(os.path.join(gen_dir, "model"))
        r2 = self._publish(reg_dir, model)
        assert r2.returncode == 0, r2.stderr
        # adopted: the model bytes did not change, only COMMIT appeared
        assert ModelRegistry(reg_dir).latest().generation == 1
        assert content_signature(
            os.path.join(gen_dir, "model")
        ) == model_sig
        assert os.path.isfile(os.path.join(gen_dir, "COMMIT"))


class TestDriftAlignment:
    def test_no_drift_is_bitwise(self):
        imap = IndexMap.build(["a\t", "b\t", "c\t"])
        parent = {"a\t": 0.1234567, "b\t": -2.5e-8, "c\t": 3.0}
        report = DriftReport()
        vec = align_coefficients(parent, imap, report=report)
        assert report.no_drift
        expected = np.zeros(3, np.float32)
        for k, v in parent.items():
            expected[imap.get_index(k)] = np.float32(v)
        assert vec.dtype == np.float32
        assert np.array_equal(vec, expected)

    def test_vocab_grow_zero_inits_new_terms(self):
        imap = IndexMap.build(["a\t", "b\t", "new\t"])
        report = DriftReport()
        vec = align_coefficients(
            {"a\t": 1.0, "b\t": 2.0}, imap, report=report
        )
        assert vec[imap.get_index("new\t")] == 0.0
        assert report.kept == 2
        assert report.new_zero_init == 1
        assert report.dropped == 0
        assert not report.no_drift

    def test_vocab_shrink_drops_with_accounting(self):
        imap = IndexMap.build(["a\t"])
        report = DriftReport()
        vec = align_coefficients(
            {"a\t": 1.0, "gone\t": 9.0}, imap, report=report
        )
        assert vec.shape == (1,)
        assert report.dropped == 1
        assert "gone\t" in report.dropped_keys_sample

    def test_reshuffled_indices_align_by_key(self):
        """Same keys, different index assignment: values follow keys."""
        imap = IndexMap.build(["z\t", "a\t", "m\t"])  # sorted: a, m, z
        vec = align_coefficients(
            {"a\t": 1.0, "m\t": 2.0, "z\t": 3.0}, imap
        )
        assert vec[imap.get_index("a\t")] == 1.0
        assert vec[imap.get_index("z\t")] == 3.0

    def _re_fixture(self):
        imap = IndexMap.build(["u0\t", "u1\t", "u2\t"])
        # projection: every entity sees all three features, global ids
        # by the map
        D = 3
        proj = np.asarray(
            [[imap.get_index(f"u{j}\t") for j in range(D)]] * 3, np.int32
        )
        return imap, proj

    def test_re_bank_no_drift_bitwise(self):
        imap, proj = self._re_fixture()
        parent = {
            "e0": {"u0\t": 0.5, "u1\t": -0.125},
            "e1": {"u2\t": 7.0},
            "e2": {"u0\t": 1e-30},
        }
        report = DriftReport()
        bank = align_re_bank(
            parent, ["e0", "e1", "e2"], proj, imap, report=report
        )
        assert report.no_drift
        assert report.kept_entities == 3
        expected = np.zeros((3, 3), np.float32)
        for e, (eid) in enumerate(["e0", "e1", "e2"]):
            for k, v in parent[eid].items():
                expected[e, imap.get_index(k)] = np.float32(v)
        assert np.array_equal(bank, expected)

    def test_re_entity_churn_prior_mean_init(self):
        imap, proj = self._re_fixture()
        parent = {
            "e0": {"u0\t": 1.0},
            "e1": {"u0\t": 3.0},
        }
        proj = np.asarray([proj[0]] * 3, np.int32)
        report = DriftReport()
        bank = align_re_bank(
            parent, ["e0", "e1", "NEW"], proj, imap, report=report
        )
        assert report.churned_entities_prior_init == 1
        # prior mean over the FULL parent population (missing-as-zero):
        # (1.0 + 3.0) / 2 entities
        assert bank[2, imap.get_index("u0\t")] == np.float32(2.0)
        assert not report.no_drift

    def test_re_dropped_entity_accounting(self):
        imap, proj = self._re_fixture()
        report = DriftReport()
        align_re_bank(
            {"kept": {"u0\t": 1.0}, "gone": {"u0\t": 5.0}},
            ["kept"], proj[:1], imap, report=report,
        )
        assert report.dropped_entities == 1
        assert report.kept_entities == 1


def _write_avro_partitions(dirname, n_files, rows, d=12, k=4, seed=0):
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container

    rng = np.random.default_rng(seed)
    os.makedirs(dirname, exist_ok=True)
    for fi in range(n_files):
        recs = []
        for i in range(rows):
            ix = rng.integers(0, d, size=k)
            vs = rng.normal(size=k)
            recs.append({
                "uid": f"{fi}-{i}",
                "label": float(rng.integers(0, 2)),
                "features": [
                    {"name": f"f{int(j)}", "term": "", "value": float(v)}
                    for j, v in zip(ix, vs)
                ],
                "offset": 0.0,
                "weight": 1.0,
            })
        write_container(
            os.path.join(dirname, f"part-{fi:03d}.avro"),
            schemas.TRAINING_EXAMPLE_AVRO, recs,
        )


class TestStatsCache:
    def _fmt(self):
        from photon_ml_tpu.io.input_format import AvroInputDataFormat

        return AvroInputDataFormat(add_intercept=True)

    def test_cached_scan_matches_uncached_exactly(self, tmp_path):
        train = str(tmp_path / "train")
        _write_avro_partitions(train, 3, 40)
        fmt = self._fmt()
        imap_ref, stats_ref = fmt.stream_scan([train])
        imap, stats, cs = cached_scan_stream(
            [train], fmt, str(tmp_path / "cache")
        )
        assert dict(imap.items()) == dict(imap_ref.items())
        assert (stats.num_rows, stats.max_nnz) == (
            stats_ref.num_rows, stats_ref.max_nnz,
        )
        assert cs.partitions == 3 and cs.scanned == 3 and cs.cached == 0

    def test_second_scan_touches_zero_partitions(self, tmp_path):
        train = str(tmp_path / "train")
        _write_avro_partitions(train, 3, 40)
        fmt = self._fmt()
        cache = str(tmp_path / "cache")
        cached_scan_stream([train], fmt, cache)
        _imap, _stats, cs = cached_scan_stream([train], fmt, cache)
        assert cs.scanned == 0 and cs.cached == 3

    def test_appended_partition_scans_only_the_new_file(self, tmp_path):
        train = str(tmp_path / "train")
        _write_avro_partitions(train, 3, 40)
        fmt = self._fmt()
        cache = str(tmp_path / "cache")
        cached_scan_stream([train], fmt, cache)
        _write_avro_partitions(train, 1, 25, seed=99)  # part-000 rewrite?
        # seed=99 rewrites part-000: content changed -> rescan of that
        # one; plus append a genuinely new file
        _write_avro_partitions(
            str(tmp_path / "extra"), 1, 25, seed=42
        )
        os.replace(
            str(tmp_path / "extra" / "part-000.avro"),
            os.path.join(train, "part-900.avro"),
        )
        imap, stats, cs = cached_scan_stream([train], fmt, cache)
        assert cs.partitions == 4
        assert cs.scanned == 2  # the rewritten file + the appended one
        assert cs.cached == 2
        # and the result is still exactly the uncached scan
        imap_ref, stats_ref = fmt.stream_scan([train])
        assert dict(imap.items()) == dict(imap_ref.items())
        assert (stats.num_rows, stats.max_nnz) == (
            stats_ref.num_rows, stats_ref.max_nnz,
        )

    def test_corrupt_entry_quarantines_and_rescans(self, tmp_path):
        train = str(tmp_path / "train")
        _write_avro_partitions(train, 2, 30)
        fmt = self._fmt()
        cache = str(tmp_path / "cache")
        _imap_ref, stats_ref, _ = cached_scan_stream([train], fmt, cache)
        vdir = os.path.join(cache, "v1")
        entry = sorted(os.listdir(vdir))[0]
        with open(os.path.join(vdir, entry), "w") as f:
            f.write("{torn json")
        imap, stats, cs = cached_scan_stream([train], fmt, cache)
        assert cs.quarantined == 1
        assert cs.scanned == 1 and cs.cached == 1
        assert any(
            name.endswith(".corrupt") for name in os.listdir(vdir)
        )
        assert (stats.num_rows, stats.max_nnz) == (
            stats_ref.num_rows, stats_ref.max_nnz,
        )

    def test_summary_path_matches_fused_scan(self, tmp_path):
        train = str(tmp_path / "train")
        _write_avro_partitions(train, 3, 40)
        fmt = self._fmt()
        imap_ref, stats_ref, summary_ref = fmt.stream_scan_with_summary(
            [train]
        )
        imap, stats, summary, cs = cached_scan_stream_with_summary(
            [train], fmt, str(tmp_path / "cache")
        )
        assert dict(imap.items()) == dict(imap_ref.items())
        assert stats.num_rows == stats_ref.num_rows
        np.testing.assert_allclose(
            np.asarray(summary.mean), np.asarray(summary_ref.mean),
            rtol=1e-6, atol=1e-7,
        )
        np.testing.assert_allclose(
            np.asarray(summary.variance),
            np.asarray(summary_ref.variance), rtol=1e-5, atol=1e-6,
        )
        # warm rerun: zero partitions re-read, same summary
        imap2, _stats2, summary2, cs2 = cached_scan_stream_with_summary(
            [train], fmt, str(tmp_path / "cache")
        )
        assert cs2.scanned == 0
        assert np.array_equal(
            np.asarray(summary.mean), np.asarray(summary2.mean)
        )

    def test_scan_only_entry_upgrades_for_summary(self, tmp_path):
        """A cache populated by the scan-only path must rescan for
        moments (has_moments=False), not serve empty partials."""
        train = str(tmp_path / "train")
        _write_avro_partitions(train, 2, 20)
        fmt = self._fmt()
        cache = str(tmp_path / "cache")
        cached_scan_stream([train], fmt, cache)
        _i, _s, summary, cs = cached_scan_stream_with_summary(
            [train], fmt, cache
        )
        assert cs.scanned == 2  # upgraded, not trusted
        _iref, _sref, summary_ref = fmt.stream_scan_with_summary([train])
        np.testing.assert_allclose(
            np.asarray(summary.mean), np.asarray(summary_ref.mean),
            rtol=1e-6, atol=1e-7,
        )


class TestGates:
    def _chunks(self, cand_shift=0.0, n=400, seed=0):
        rng = np.random.default_rng(seed)
        z = rng.normal(size=n)
        y = (1 / (1 + np.exp(-z)) > rng.uniform(size=n)).astype(
            np.float64
        )
        w = np.ones(n)
        par = z
        cand = z + cand_shift * rng.normal(size=n)
        return [(cand, par, y, w)]

    def test_identical_models_pass(self):
        from photon_ml_tpu.task import TaskType

        report = evaluate_gates(
            self._chunks(0.0), TaskType.LOGISTIC_REGRESSION,
            candidate_norm=1.0, parent_norm=1.0,
        )
        assert report.verdict == "PASS" and report.passed
        assert report.checks["auc"]["passed"]

    def test_auc_regression_named_verdict(self):
        from photon_ml_tpu.task import TaskType

        # candidate = noise: AUC collapses to ~0.5
        rng = np.random.default_rng(1)
        chunks = self._chunks(0.0)
        cand, par, y, w = chunks[0]
        chunks = [(rng.normal(size=len(y)), par, y, w)]
        report = evaluate_gates(chunks, TaskType.LOGISTIC_REGRESSION)
        assert report.verdict == "AUC_REGRESSION"
        assert not report.checks["auc"]["passed"]

    def test_coef_norm_blowup_named_verdict(self):
        from photon_ml_tpu.task import TaskType

        report = evaluate_gates(
            self._chunks(0.0), TaskType.LOGISTIC_REGRESSION,
            candidate_norm=1e4, parent_norm=1.0,
        )
        assert report.verdict == "COEF_NORM_BLOWUP"
        assert report.checks["coef_norm"]["ratio"] == pytest.approx(1e4)

    def test_prediction_drift_named_verdict(self):
        from photon_ml_tpu.task import TaskType

        report = evaluate_gates(
            self._chunks(5.0),
            TaskType.LOGISTIC_REGRESSION,
            config=GateConfig(
                max_auc_drop=1.0, max_prediction_drift=0.1
            ),
        )
        assert report.verdict == "PREDICTION_DRIFT"

    def test_rmse_gate_on_regression_task(self):
        from photon_ml_tpu.task import TaskType

        rng = np.random.default_rng(2)
        n = 300
        y = rng.normal(size=n)
        par = y + 0.1 * rng.normal(size=n)
        cand = y + 3.0 * rng.normal(size=n)
        report = evaluate_gates(
            [(cand, par, y, np.ones(n))], TaskType.LINEAR_REGRESSION,
        )
        assert report.verdict == "RMSE_REGRESSION"

    def test_empty_holdout_refuses(self):
        from photon_ml_tpu.task import TaskType

        report = evaluate_gates([], TaskType.LOGISTIC_REGRESSION)
        assert report.verdict == "EMPTY_HOLDOUT"

    def test_report_round_trips_through_manifest(
        self, registry, model_dir, tmp_path
    ):
        """The gate report survives the publish -> manifest -> load
        round trip verbatim, pass AND fail."""
        from photon_ml_tpu.task import TaskType

        passing = evaluate_gates(
            self._chunks(0.0), TaskType.LOGISTIC_REGRESSION,
            candidate_norm=1.0, parent_norm=1.0,
        )
        info = registry.publish(
            model_dir, gate_report=passing.as_dict()
        )
        loaded = GateReport.from_dict(info.manifest["gates"])
        assert loaded.verdict == "PASS"
        assert loaded.as_dict() == passing.as_dict()
        failing = evaluate_gates(
            self._chunks(0.0), TaskType.LOGISTIC_REGRESSION,
            candidate_norm=1e6, parent_norm=1.0,
        )
        bad = _write_model(str(tmp_path / "bad"), b"BAD")
        with pytest.raises(RefusedCandidate):
            registry.publish(bad, parent=1, gate_report=failing.as_dict())
        rec = registry.refused_candidates()[0]
        assert GateReport.from_dict(rec["gates"]).verdict == (
            "COEF_NORM_BLOWUP"
        )


class _StubSwapper:
    """ServingModel-shaped stub: records swaps, optional failure."""

    def __init__(self):
        self.swapped_dirs = []
        self.fail_next = False

    def stage_and_swap(self, model_dir, **kw):
        from photon_ml_tpu.serving.swap import SwapResult

        self.swapped_dirs.append(model_dir)
        if self.fail_next:
            self.fail_next = False
            return SwapResult(
                ok=False, generation=0, rolled_back=True, error="boom"
            )
        return SwapResult(ok=True, generation=len(self.swapped_dirs))


class TestWatcher:
    def _watcher(self, registry, swapper, **kw):
        from photon_ml_tpu.registry import RegistryWatcher

        kw.setdefault("poll_s", 30.0)  # poke-driven in tests
        return RegistryWatcher(registry, swapper, **kw)

    def test_promotes_new_generation(self, registry, model_dir):
        g1 = registry.publish(model_dir)
        swapper = _StubSwapper()
        w = self._watcher(registry, swapper, initial_generation=g1)
        w._check_registry()
        assert swapper.swapped_dirs == []  # nothing newer
        m2 = _write_model(
            os.path.join(registry.root, os.pardir, "m2"), b"G2"
        )
        registry.publish(m2, parent=1)
        w._check_registry()
        assert swapper.swapped_dirs == [
            registry.generation(2).model_dir
        ]
        lin = w.lineage()
        assert lin["registry_generation"] == 2
        assert lin["parent"] == 1
        assert lin["lineage"] == [2, 1]
        assert lin["last_swap"]["action"] == "swap"

    def test_health_regression_rolls_back_and_quarantines(
        self, registry, model_dir, tmp_path
    ):
        g1 = registry.publish(model_dir)
        m2 = _write_model(str(tmp_path / "m2"), b"G2")
        registry.publish(m2, parent=1)
        swapper = _StubSwapper()
        w = self._watcher(
            registry, swapper, initial_generation=g1,
            policy=RollbackPolicy(
                window=8, min_requests=4, max_unhealthy_rate=0.5
            ),
        )
        w._check_registry()  # promote gen 2, watch window armed
        assert w._watching_swap
        for _ in range(6):
            w.observe_outcome(degraded=True)
        assert w._rollback_wanted
        ok = w.rollback(reason="post-swap health regression")
        assert ok
        # the bad generation is gone from the loader view, parent rules
        assert registry.latest().generation == 1
        assert w.lineage()["registry_generation"] == 1
        assert w.lineage()["last_swap"]["action"] == "rollback"
        # the rollback swap targeted the PARENT artifact
        assert swapper.swapped_dirs[-1] == (
            registry.generation(1).model_dir
        )
        # quarantined generations never re-promote
        w._check_registry()
        assert len(swapper.swapped_dirs) == 2

    def test_healthy_window_never_rolls_back(
        self, registry, model_dir, tmp_path
    ):
        g1 = registry.publish(model_dir)
        m2 = _write_model(str(tmp_path / "m2"), b"G2")
        registry.publish(m2, parent=1)
        swapper = _StubSwapper()
        w = self._watcher(
            registry, swapper, initial_generation=g1,
            policy=RollbackPolicy(
                window=8, min_requests=4, max_unhealthy_rate=0.5
            ),
        )
        w._check_registry()
        for _ in range(50):
            w.observe_outcome(degraded=False)
        assert not w._rollback_wanted
        assert registry.latest().generation == 2

    def test_rollback_without_parent_is_refused(
        self, registry, model_dir
    ):
        g1 = registry.publish(model_dir)
        swapper = _StubSwapper()
        w = self._watcher(registry, swapper, initial_generation=g1)
        assert w.rollback() is False
        assert registry.latest().generation == 1


class TestServingIntegration:
    """Watcher + REAL ServingModel banks: promotion under the frontend,
    bitwise parent restore on rollback, status lineage + rollback op."""

    @pytest.fixture()
    def game_stack(self, rng, tmp_path):
        from tests.test_serving import SHARDS, synth_records
        from photon_ml_tpu.game.data import build_game_dataset
        from photon_ml_tpu.game.model import (
            FixedEffectModel, GameModel,
        )
        from photon_ml_tpu.game.model_io import (
            LoadedGameModel, save_game_model,
        )
        from photon_ml_tpu.models.coefficients import Coefficients
        from photon_ml_tpu.models.glm import create_model
        from photon_ml_tpu.task import TaskType
        import jax.numpy as jnp

        recs = synth_records(rng)
        ds = build_game_dataset(recs, [SHARDS[0]], [])

        def save_scaled(path, scale):
            lm = LoadedGameModel()
            lm.fixed_effects["global"] = (
                "g",
                {
                    f"g{j}\t": float(rng.normal()) * scale
                    for j in range(5)
                },
            )
            shard_id, means = lm.fixed_effects["global"]
            imap = ds.shards[shard_id].index_map
            wvec = np.zeros((imap.size,), np.float32)
            for k, v in means.items():
                i = imap.get_index(k)
                if i >= 0:
                    wvec[i] = v
            gm = GameModel({
                "global": FixedEffectModel(
                    create_model(
                        TaskType.LOGISTIC_REGRESSION,
                        Coefficients(jnp.asarray(wvec)),
                    ),
                    shard_id,
                )
            })
            save_game_model(gm, ds, path)
            return path

        return ds, save_scaled, str(tmp_path)

    def test_rollback_restores_parent_bank_bitwise(
        self, game_stack, tmp_path
    ):
        from photon_ml_tpu.registry import RegistryWatcher
        from photon_ml_tpu.serving import ServingModel
        import jax

        ds, save_scaled, base = game_stack
        registry = ModelRegistry(os.path.join(base, "registry"))
        g1_dir = save_scaled(os.path.join(base, "m1"), 1.0)
        g2_dir = save_scaled(os.path.join(base, "m2"), -2.0)
        g1 = registry.publish(g1_dir)
        registry.publish(g2_dir, parent=1)

        imaps = {"g": ds.shards["g"].index_map}
        widths = {"g": ds.shards["g"].indices.shape[1]}
        sm = ServingModel.load(
            g1.model_dir, imaps, widths, ladder=(1, 8)
        )
        g1_arrays = jax.tree_util.tree_map(
            np.asarray, sm.current().arrays
        )
        w = RegistryWatcher(
            registry, sm, poll_s=30.0, initial_generation=g1,
            policy=RollbackPolicy(
                window=8, min_requests=4, max_unhealthy_rate=0.5
            ),
        )
        w._check_registry()
        assert sm.generation == 2
        for _ in range(6):
            w.observe_outcome(failed=True)
        assert w.rollback(reason="test regression")
        assert registry.latest().generation == 1
        # the restored bank is BITWISE the original generation-1 bank
        restored = jax.tree_util.tree_map(
            np.asarray, sm.current().arrays
        )
        flat_a, _ = jax.tree_util.tree_flatten(g1_arrays)
        flat_b, _ = jax.tree_util.tree_flatten(restored)
        assert len(flat_a) == len(flat_b)
        for a, b in zip(flat_a, flat_b):
            assert np.array_equal(a, b)

    def test_frontend_status_lineage_and_rollback_op(
        self, game_stack, tmp_path
    ):
        from photon_ml_tpu.registry import RegistryWatcher
        from photon_ml_tpu.serving import (
            MicroBatcher,
            ServingFrontend,
            ServingMetrics,
            ServingModel,
        )
        from tests.test_serving_frontend import Client

        ds, save_scaled, base = game_stack
        registry = ModelRegistry(os.path.join(base, "registry"))
        g1 = registry.publish(save_scaled(os.path.join(base, "m1"), 1.0))
        registry.publish(
            save_scaled(os.path.join(base, "m2"), -2.0), parent=1
        )
        imaps = {"g": ds.shards["g"].index_map}
        widths = {"g": ds.shards["g"].indices.shape[1]}
        sm = ServingModel.load(
            g1.model_dir, imaps, widths, ladder=(1, 8)
        )
        watcher = RegistryWatcher(
            registry, sm, poll_s=30.0, initial_generation=g1,
        )
        metrics = ServingMetrics()
        batcher = MicroBatcher(sm.current, sm.programs, metrics)
        fe = ServingFrontend(
            batcher, sm, [],
            metrics=metrics, port=0,
            lineage_provider=watcher.lineage,
            rollback_handler=watcher.rollback,
        ).start()
        try:
            watcher._check_registry()  # promote gen 2
            c = Client(fe.port)
            status = c.ask({"op": "status"})
            assert status["registry"]["registry_generation"] == 2
            assert status["registry"]["parent"] == 1
            assert status["registry"]["lineage"] == [2, 1]
            assert status["last_swap"]["ok"] is True
            resp = c.ask({"op": "rollback"})
            assert resp["status"] == "ok" and resp["rolled_back"]
            status = c.ask({"op": "status"})
            assert status["registry"]["registry_generation"] == 1
            assert (
                status["registry"]["last_swap"]["action"] == "rollback"
            )
            assert registry.latest().generation == 1
            c.close()
        finally:
            fe.stop_accepting()
            batcher.drain(5.0)
            fe.close()
            batcher.close()
