"""Batched λ-grid training (ISSUE 5): the vmapped grid engine against the
warm-started sequential path.

Pins the contract, not just the happy path:
- per-λ parity with the sequential trainer within the PERF_NOTES fp32
  envelopes (rtol 2e-3 class for the LBFGS family, tighter for TRON),
  on both the scatter and tiled kernels;
- active-mask freeze semantics — a converged member's state is
  BIT-stable while stragglers run on;
- one compiled program serves any same-shape grid (0 re-lowerings) and
  the whole grid's result scalars come back in ONE counted readback;
- the --grid-mode auto policy's memory-budget / streaming fallbacks;
- the feature-sharded grid twin on the (data, model) mesh.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu import training
from photon_ml_tpu.data.batch import SparseBatch
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.task import TaskType

LAMBDAS = [10.0, 1.0, 0.1, 0.01]


def _synth_batch(rng, n=500, d=48, k=6, weighted=False, offsets=False):
    indices = rng.integers(0, d, size=(n, k)).astype(np.int32)
    values = rng.normal(size=(n, k)).astype(np.float32)
    labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
    return SparseBatch(
        indices=jnp.asarray(indices),
        values=jnp.asarray(values),
        labels=jnp.asarray(labels),
        offsets=jnp.asarray(
            rng.normal(size=n).astype(np.float32) * 0.1
            if offsets else np.zeros(n, np.float32)
        ),
        weights=jnp.asarray(
            rng.uniform(0.5, 2.0, size=n).astype(np.float32)
            if weighted else np.ones(n, np.float32)
        ),
    )


def _assert_grid_parity(r_seq, r_bat, *, value_rtol, coef_atol):
    for lam in r_seq:
        vs, vb = float(r_seq[lam].value), float(r_bat[lam].value)
        assert vb == pytest.approx(vs, rel=value_rtol), lam
        np.testing.assert_allclose(
            np.asarray(r_bat[lam].coefficients),
            np.asarray(r_seq[lam].coefficients),
            atol=coef_atol,
            err_msg=f"lambda={lam}",
        )


class TestGridParityScatter:
    @pytest.mark.parametrize(
        "opt,reg,alpha",
        [
            (OptimizerType.LBFGS, RegularizationType.L2, None),
            (OptimizerType.TRON, RegularizationType.L2, None),
            (OptimizerType.LBFGS, RegularizationType.ELASTIC_NET, 0.5),
        ],
    )
    def test_matches_cold_sequential_exactly(self, rng, opt, reg, alpha):
        """Against the UN-warm-started sequential path the batched grid
        walks the same per-member iterate sequence — near-exact (the only
        noise is vmap's fused-reduction ordering)."""
        batch = _synth_batch(rng, weighted=True, offsets=True)
        kw = dict(
            optimizer_type=opt, regularization_type=reg,
            regularization_weights=LAMBDAS, elastic_net_alpha=alpha,
        )
        _, r_seq = training.train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, 48, warm_start=False, **kw
        )
        _, r_bat = training.train_grid_batched(
            batch, TaskType.LOGISTIC_REGRESSION, 48, **kw
        )
        # values effectively exact; coefficients see the fp32 reorder
        # noise amplified through line-search branch points (PERF_NOTES
        # r8 "~1e-4 relative" class — atol 1e-3 is the seed-safe margin)
        _assert_grid_parity(
            r_seq, r_bat, value_rtol=1e-5, coef_atol=1e-3
        )

    def test_matches_warm_sequential_within_envelope(self, rng):
        """Against the DEFAULT warm-started sequential path both land on
        the same per-λ optimum, reached along different iterate paths —
        the PERF_NOTES rtol-2e-3-class LBFGS envelope."""
        batch = _synth_batch(rng)
        kw = dict(
            regularization_type=RegularizationType.L2,
            regularization_weights=LAMBDAS,
        )
        _, r_seq = training.train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, 48, warm_start=True, **kw
        )
        _, r_bat = training.train_grid_batched(
            batch, TaskType.LOGISTIC_REGRESSION, 48, **kw
        )
        _assert_grid_parity(r_seq, r_bat, value_rtol=2e-3, coef_atol=5e-3)

    def test_tron_matches_warm_sequential_tight(self, rng):
        """TRON's trust-region walk is insensitive to the start point on
        these convex fits — tighter envelope than the LBFGS class."""
        batch = _synth_batch(rng)
        kw = dict(
            optimizer_type=OptimizerType.TRON,
            regularization_type=RegularizationType.L2,
            regularization_weights=LAMBDAS,
        )
        _, r_seq = training.train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, 48, warm_start=True, **kw
        )
        _, r_bat = training.train_grid_batched(
            batch, TaskType.LOGISTIC_REGRESSION, 48, **kw
        )
        _assert_grid_parity(r_seq, r_bat, value_rtol=1e-4, coef_atol=1e-3)

    def test_models_box_and_normalization_broadcast(self, rng):
        """Box constraints, normalization (shift/factor) and offsets all
        broadcast across the grid axis: batched models equal the cold
        sequential models in the ORIGINAL feature space."""
        from photon_ml_tpu.ops.normalization import NormalizationContext
        from photon_ml_tpu.optim.common import BoxConstraints

        d = 48
        batch = _synth_batch(rng, d=d, offsets=True)
        norm = NormalizationContext(
            factor=jnp.asarray(
                rng.uniform(0.5, 2.0, size=d).astype(np.float32)
            ),
            shift=jnp.asarray(
                rng.normal(size=d).astype(np.float32) * 0.1
            ),
        )
        box = BoxConstraints(
            lower=jnp.full((d,), -0.3, jnp.float32),
            upper=jnp.full((d,), 0.3, jnp.float32),
        )
        kw = dict(
            regularization_type=RegularizationType.L2,
            regularization_weights=LAMBDAS,
            normalization=norm, box=box, compute_variances=True,
        )
        m_seq, _ = training.train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, warm_start=False, **kw
        )
        m_bat, _ = training.train_grid_batched(
            batch, TaskType.LOGISTIC_REGRESSION, d, **kw
        )
        for lam in LAMBDAS:
            np.testing.assert_allclose(
                np.asarray(m_bat[lam].coefficients.means),
                np.asarray(m_seq[lam].coefficients.means),
                atol=1e-4, err_msg=f"lambda={lam}",
            )
            np.testing.assert_allclose(
                np.asarray(m_bat[lam].coefficients.variances),
                np.asarray(m_seq[lam].coefficients.variances),
                rtol=1e-3, err_msg=f"lambda={lam}",
            )


class TestGridParityTiled:
    def test_tiled_kernel_matches_sequential(self, rng):
        """The tiled kernel's grid path (one fused schedule walk for the
        whole grid via the custom_vmap rule) against the sequential tiled
        fits — the bf16x2w-vs-exact-f32 pass difference bounds the drift
        (~1e-5 relative, the documented mxu envelope)."""
        from photon_ml_tpu.ops.tiled_sparse import (
            TileParams,
            tiled_batch_from_sparse,
        )

        d = 90
        batch = _synth_batch(rng, n=300, d=d)
        tb = tiled_batch_from_sparse(
            batch, d, params=TileParams(s_hi=8, s_lo=8, chunk=32)
        )
        kw = dict(
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0, 0.1],
            kernel="tiled",
        )
        _, r_seq = training.train_generalized_linear_model(
            tb, TaskType.LOGISTIC_REGRESSION, d, warm_start=False, **kw
        )
        _, r_bat = training.train_grid_batched(
            tb, TaskType.LOGISTIC_REGRESSION, d, **kw
        )
        _assert_grid_parity(r_seq, r_bat, value_rtol=2e-3, coef_atol=5e-3)


class TestFreezeSemantics:
    def test_converged_member_is_bit_stable(self, rng):
        """Active-mask freeze: once a member converges, later while_loop
        trips (driven by the stragglers) must not move it AT ALL. Two
        runs whose only difference is how long the stragglers run must
        agree BITWISE on the early-converged member."""
        batch = _synth_batch(rng)
        problem_short = create_glm_problem(
            TaskType.LOGISTIC_REGRESSION, 48,
            config=OptimizerConfig(
                optimizer_type=OptimizerType.LBFGS, max_iter=10,
                tolerance=1e-9,
            ),
            regularization=RegularizationContext(RegularizationType.L2),
        )
        problem_long = create_glm_problem(
            TaskType.LOGISTIC_REGRESSION, 48,
            config=OptimizerConfig(
                optimizer_type=OptimizerType.LBFGS, max_iter=60,
                tolerance=1e-9,
            ),
            regularization=RegularizationContext(RegularizationType.L2),
        )
        # member 0: heavy regularization, converges in a few trips;
        # member 1: near-unregularized at a tight tolerance — the
        # straggler that keeps the batched loop running
        grid = [1000.0, 1e-6]
        _, r_short = problem_short.run_grid(batch, grid)
        _, r_long = problem_long.run_grid(batch, grid)
        it0 = int(r_short.iterations[0])
        assert it0 < 10, "fast member unexpectedly slow"
        assert int(r_long.iterations[1]) > it0, (
            "straggler should out-iterate the fast member"
        )
        # fast member froze at the same trip in both programs: bitwise
        # identical state even though the long run kept looping
        assert int(r_long.iterations[0]) == it0
        assert np.array_equal(
            np.asarray(r_short.coefficients[0]),
            np.asarray(r_long.coefficients[0]),
        ), "converged member's coefficients moved after convergence"
        assert float(r_short.value[0]) == float(r_long.value[0])
        assert int(r_short.reason[0]) == int(r_long.reason[0])


class TestCompileAndReadbackContract:
    def test_one_program_serves_any_same_shape_grid(self, rng):
        """The λ vector is a TRACED argument: after the first grid solve
        compiles, a different grid of the same shape re-lowers NOTHING
        (0 jit lowerings) — the 1-compile-for-the-whole-grid contract."""
        import jax._src.test_util as jtu

        batch = _synth_batch(rng)
        problem = create_glm_problem(
            TaskType.LOGISTIC_REGRESSION, 48,
            config=OptimizerConfig(optimizer_type=OptimizerType.LBFGS),
            regularization=RegularizationContext(RegularizationType.L2),
        )
        problem.run_grid(batch, LAMBDAS)  # compile once
        with jtu.count_jit_and_pmap_lowerings() as count:
            _, result = problem.run_grid(batch, [5.0, 0.5, 0.05, 2.0])
        assert count[0] == 0, (
            f"same-shape grid re-lowered {count[0]} program(s)"
        )
        assert result.coefficients.shape == (4, 48)

    def test_whole_grid_is_one_batched_readback(self, rng):
        """run_grid leaves every scalar device-resident (0 readbacks);
        grid_result_scalars then materializes the WHOLE grid in exactly
        ONE counted overlap.device_get."""
        batch = _synth_batch(rng)
        models, results = training.train_grid_batched(
            batch, TaskType.LOGISTIC_REGRESSION, 48,
            regularization_type=RegularizationType.L2,
            regularization_weights=LAMBDAS,
        )
        overlap.reset_readback_stats()
        scalars = training.grid_result_scalars(results)
        assert overlap.readback_stats() == 1
        assert set(scalars) == set(LAMBDAS)
        for lam, (iters, value, reason) in scalars.items():
            assert iters >= 1 and np.isfinite(value) and reason != 0


class TestGridModePolicy:
    def test_resolve_modes(self):
        rgm = training.resolve_grid_mode
        common = dict(num_weights=4, dim=1000)
        assert rgm("sequential", **common) == "sequential"
        assert rgm("batched", **common) == "batched"
        assert rgm("auto", **common) == "batched"
        # single-member grids have nothing to batch
        assert rgm("auto", num_weights=1, dim=1000) == "sequential"
        # budget fallback: the G x d state bank exceeds the budget
        assert rgm(
            "auto", num_weights=4, dim=1 << 20,
            memory_budget_bytes=1 << 20,
        ) == "sequential"
        bank = training.grid_bank_bytes(4, 1 << 20)
        assert rgm(
            "auto", num_weights=4, dim=1 << 20,
            memory_budget_bytes=bank,
        ) == "batched"
        # streaming: auto falls back, explicit batched is an error
        assert rgm("auto", streaming=True, **common) == "sequential"
        with pytest.raises(ValueError, match="streaming"):
            rgm("batched", streaming=True, **common)
        with pytest.raises(ValueError, match="unknown grid mode"):
            rgm("eager", **common)

    def test_tron_bank_is_smaller_than_lbfgs(self):
        assert training.grid_bank_bytes(
            4, 1000, OptimizerType.TRON
        ) < training.grid_bank_bytes(4, 1000, OptimizerType.LBFGS)


class TestFeatureShardedGrid:
    @pytest.mark.parametrize(
        "opt,reg,alpha",
        [
            (OptimizerType.LBFGS, RegularizationType.L2, None),
            (OptimizerType.TRON, RegularizationType.L2, None),
            (OptimizerType.LBFGS, RegularizationType.ELASTIC_NET, 0.5),
        ],
    )
    def test_matches_cold_sequential(self, rng, opt, reg, alpha):
        """The shard_map(vmap(optimizer)) twin on the (data, model) mesh
        against the sequential feature-sharded sweep (cold starts)."""
        from photon_ml_tpu.parallel.mesh import (
            DATA_AXIS,
            MODEL_AXIS,
            make_mesh,
        )

        batch = _synth_batch(rng, n=320, d=56)
        mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        kw = dict(
            mesh=mesh, regularization_type=reg, elastic_net_alpha=alpha,
            regularization_weights=[1.0, 0.1, 10.0], optimizer_type=opt,
        )
        _, r_seq = training.train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, 56, warm_start=False, **kw
        )
        _, r_bat = training.train_grid_batched_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, 56, **kw
        )
        _assert_grid_parity(r_seq, r_bat, value_rtol=1e-5, coef_atol=1e-3)
