"""ISSUE 10 driver round trips: --retrain-from / --publish-registry /
--scan-cache-dir through the real GLM and GAME training drivers.

The retrain loop an operator crons: train -> publish generation 1 ->
append data -> retrain warm-started from generation 1 (scanning ONLY
the new partitions) -> gates vs the parent -> publish generation 2 with
lineage. Plus the refusal path: a poisoned retrain (label-flipped data)
fails its AUC gate, records the named verdict, and generation 2 never
exists.
"""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.registry import ModelRegistry


def _logistic_rows(rng, w, n, k, uid_prefix):
    d = len(w)
    recs = []
    for i in range(n):
        ix = rng.integers(0, d, size=k)
        vs = rng.normal(size=k)
        z = float((w[ix] * vs).sum())
        recs.append({
            "uid": f"{uid_prefix}-{i}",
            "label": float(1 / (1 + np.exp(-z)) > rng.uniform()),
            "features": [
                {"name": f"f{int(j)}", "term": "", "value": float(v)}
                for j, v in zip(ix, vs)
            ],
            "offset": 0.0,
            "weight": 1.0,
        })
    return recs


def _write_glm_dir(path, recs):
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container

    os.makedirs(path, exist_ok=True)
    write_container(
        os.path.join(path, f"part-{len(os.listdir(path)):03d}.avro"),
        schemas.TRAINING_EXAMPLE_AVRO, recs,
    )


@pytest.fixture()
def glm_world(tmp_path, rng):
    d, k = 24, 5
    w = rng.normal(size=d) * 0.8
    train = str(tmp_path / "train")
    val = str(tmp_path / "val")
    for fi in range(3):
        _write_glm_dir(train, _logistic_rows(rng, w, 150, k, f"t{fi}"))
    _write_glm_dir(val, _logistic_rows(rng, w, 400, k, "v"))
    return tmp_path, train, val, w, k


def _glm_run(tmp_path, train, val, out_name, extra=()):
    from photon_ml_tpu.cli.glm_driver import GLMDriver, params_from_args

    out = str(tmp_path / out_name)
    args = [
        "--training-data-directory", train,
        "--output-directory", out,
        "--validating-data-directory", val,
        "--regularization-weights", "1.0",
        "--num-iterations", "15",
        "--streaming", "true",
        "--delete-output-dirs-if-exist", "true",
        *extra,
    ]
    driver = GLMDriver(params_from_args(args))
    driver.run()
    with open(os.path.join(out, "metrics.json")) as f:
        return driver, json.load(f)


class TestGLMRetrainLoop:
    def test_publish_retrain_publish_with_lineage_and_scan_cache(
        self, glm_world, rng
    ):
        tmp_path, train, val, w, k = glm_world
        reg_dir = str(tmp_path / "registry")
        cache = str(tmp_path / "scan-cache")
        retrain_args = [
            "--retrain-from", reg_dir,
            "--publish-registry", reg_dir,
            "--scan-cache-dir", cache,
            # loose quality gates: this test pins MACHINERY, the tight-
            # threshold refusal path is pinned separately below
            "--gate-max-auc-drop", "0.5",
        ]
        _d1, m1 = _glm_run(tmp_path, train, val, "out1", retrain_args)
        assert m1["registry"]["published_generation"] == 1
        assert m1["registry"]["parent_generation"] is None
        assert m1["scan_cache"]["scanned"] == 3
        reg = ModelRegistry(reg_dir)
        assert reg.latest().generation == 1

        # append ONE partition and retrain: warm start + only-new scan
        _write_glm_dir(train, _logistic_rows(rng, w, 100, k, "new"))
        _d2, m2 = _glm_run(tmp_path, train, val, "out2", retrain_args)
        r = m2["registry"]
        assert r["parent_generation"] == 1
        assert r["published_generation"] == 2
        assert r["gates"]["verdict"] == "PASS"
        # the drift report: same vocab features kept (tiny synthetic
        # vocab — all 24+intercept terms recur), nothing dropped
        assert r["drift"]["dropped"] == 0
        # ONLY the appended partition was re-read
        assert m2["scan_cache"]["partitions"] == 4
        assert m2["scan_cache"]["scanned"] == 1
        assert m2["scan_cache"]["cached"] == 3
        info = reg.latest()
        assert info.generation == 2 and info.parent == 1
        assert info.manifest["gates"]["verdict"] == "PASS"
        assert reg.lineage() == [2, 1]

    def test_poisoned_retrain_is_refused_with_named_verdict(
        self, glm_world, rng
    ):
        tmp_path, train, val, w, k = glm_world
        reg_dir = str(tmp_path / "registry")
        base = [
            "--retrain-from", reg_dir,
            "--publish-registry", reg_dir,
            "--gate-max-auc-drop", "0.5",
        ]
        _glm_run(tmp_path, train, val, "out1", base)

        # poison: a flood of label-FLIPPED data swamps the signal
        flipped = _logistic_rows(rng, -w, 1200, k, "poison")
        _write_glm_dir(train, flipped)
        _d, m = _glm_run(
            tmp_path, train, val, "out2",
            [
                "--retrain-from", reg_dir,
                "--publish-registry", reg_dir,
                "--gate-max-auc-drop", "0.02",
            ],
        )
        r = m["registry"]
        assert r["published_generation"] is None
        assert r["gates"]["verdict"] == "AUC_REGRESSION"
        reg = ModelRegistry(reg_dir)
        # candidate NEVER loadable; refusal on record with the verdict
        assert [g.generation for g in reg.list_generations()] == [1]
        refusals = reg.refused_candidates()
        assert len(refusals) == 1
        assert refusals[0]["gates"]["verdict"] == "AUC_REGRESSION"

    def test_validation_rules(self, tmp_path):
        from photon_ml_tpu.cli.glm_driver import GLMParams

        with pytest.raises(ValueError, match="requires a validating"):
            GLMParams(
                train_dir="t", output_dir="o",
                retrain_from="r", publish_registry="r",
            ).validate()
        with pytest.raises(ValueError, match="streaming"):
            GLMParams(
                train_dir="t", output_dir="o", scan_cache_dir="c",
            ).validate()


def _game_rows(rng, w_g, w_u, n, uid_prefix, *, flip=False):
    n_users, d_u = w_u.shape
    d_g = len(w_g)
    sign = -1.0 if flip else 1.0
    recs = []
    for i in range(n):
        u = int(rng.integers(0, n_users))
        xg = rng.normal(size=d_g)
        xu = rng.normal(size=d_u)
        z = sign * float(xg @ w_g + xu @ w_u[u])
        recs.append({
            "uid": f"{uid_prefix}-{i}",
            "response": float(1 / (1 + np.exp(-z)) > rng.uniform()),
            "metadataMap": {"userId": f"user{u}"},
            "features": [
                {"name": f"g{j}", "term": "", "value": float(xg[j])}
                for j in range(d_g)
            ],
            "userFeatures": [
                {"name": f"u{j}", "term": "", "value": float(xu[j])}
                for j in range(d_u)
            ],
        })
    return recs


def _write_game_dir(path, recs):
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container

    schema = {
        "name": "GameExample", "type": "record",
        "fields": [
            {"name": "uid", "type": ["null", "string"], "default": None},
            {"name": "response", "type": "double"},
            {"name": "metadataMap",
             "type": ["null", {"type": "map", "values": "string"}],
             "default": None},
            {"name": "features",
             "type": {"type": "array", "items": schemas.FEATURE_AVRO}},
            {"name": "userFeatures",
             "type": {"type": "array", "items": "FeatureAvro"}},
        ],
    }
    os.makedirs(path, exist_ok=True)
    write_container(
        os.path.join(path, f"part-{len(os.listdir(path))}.avro"),
        schema, recs,
    )


def _game_run(tmp_path, train, val, out_name, extra=()):
    from photon_ml_tpu.cli.game_training_driver import (
        GameTrainingDriver,
        params_from_args,
    )

    out = str(tmp_path / out_name)
    args = [
        "--train-input-dirs", train,
        "--output-dir", out,
        "--validate-input-dirs", val,
        "--task-type", "LOGISTIC_REGRESSION",
        "--feature-shard-id-to-feature-section-keys-map",
        "globalShard:features|userShard:userFeatures",
        "--fixed-effect-data-configurations", "global:globalShard,1",
        "--fixed-effect-optimization-configurations",
        "global:20,1e-6,0.5,1,TRON,L2",
        "--random-effect-data-configurations",
        "per-user:userId,userShard,1,none,none,none,identity",
        "--random-effect-optimization-configurations",
        "per-user:20,1e-6,1.0,1,LBFGS,L2",
        "--num-iterations", "2",
        "--model-output-mode", "BEST",
        "--delete-output-dir-if-exists", "true",
        *extra,
    ]
    driver = GameTrainingDriver(params_from_args(args))
    driver.run()
    with open(os.path.join(out, "metrics.json")) as f:
        return driver, json.load(f)


class TestGameRetrainLoop:
    def test_warm_start_lineage_and_entity_drift(self, tmp_path, rng):
        n_users, d_g, d_u = 6, 5, 3
        w_g = np.linspace(-1, 1, d_g)
        w_u = rng.normal(size=(n_users, d_u))
        train = str(tmp_path / "train")
        val = str(tmp_path / "val")
        _write_game_dir(train, _game_rows(rng, w_g, w_u, 250, "t"))
        _write_game_dir(val, _game_rows(rng, w_g, w_u, 250, "v"))
        reg_dir = str(tmp_path / "registry")
        extra = [
            "--retrain-from", reg_dir,
            "--publish-registry", reg_dir,
            "--gate-max-auc-drop", "0.5",
        ]
        _d1, m1 = _game_run(tmp_path, train, val, "out1", extra)
        assert m1["registry"]["published_generation"] == 1
        reg = ModelRegistry(reg_dir)
        assert reg.latest().generation == 1

        # append data containing a NEW user (entity churn)
        w_u2 = np.concatenate([w_u, rng.normal(size=(1, d_u))])
        _write_game_dir(
            train, _game_rows(rng, w_g, w_u2, 120, "new")
        )
        _d2, m2 = _game_run(tmp_path, train, val, "out2", extra)
        r = m2["registry"]
        assert r["parent_generation"] == 1
        assert r["published_generation"] == 2
        assert r["gates"]["verdict"] == "PASS"
        drift = r["drift"]
        assert set(drift) == {"global", "per-user"}
        assert drift["global"]["kept"] == d_g + 1  # + intercept
        assert drift["per-user"]["kept_entities"] == n_users
        assert drift["per-user"]["churned_entities_prior_init"] == 1
        info = reg.latest()
        assert info.generation == 2 and info.parent == 1
        assert reg.lineage() == [2, 1]

    def test_retrain_validation_rules(self):
        from photon_ml_tpu.cli.game_training_driver import (
            GameTrainingParams,
        )
        from photon_ml_tpu.game.config import (
            FixedEffectDataConfiguration,
        )

        base = dict(
            train_input_dirs=["t"], output_dir="o",
            fixed_effect_data_configs={
                "global": FixedEffectDataConfiguration("globalShard")
            },
            fixed_effect_opt_configs={"global": "x"},
        )
        with pytest.raises(ValueError, match="streaming"):
            GameTrainingParams(
                **base, retrain_from="r", streaming=True,
            ).validate()
        with pytest.raises(ValueError, match="validate-input-dirs"):
            GameTrainingParams(
                **base, retrain_from="r", publish_registry="r",
            ).validate()
