"""Host-device overlap layer (parallel/overlap.py): deferred-readback
discipline (ONE batched fetch per GAME CD iteration, zero per-bucket
readbacks), overlap == serial parity, pipelined == serial staging parity,
and async checkpoint IO ordering."""


import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.game import (
    CoordinateDescent,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
    RandomEffectDataConfiguration,
    RandomEffectOptimizationProblem,
    build_game_dataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.game import FeatureShardConfiguration
from photon_ml_tpu.ops.losses import LOGISTIC
from photon_ml_tpu.optim import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.task import TaskType

SHARDS = [
    FeatureShardConfiguration("globalShard", ["features"], add_intercept=True),
    FeatureShardConfiguration("userShard", ["userFeatures"], add_intercept=True),
]


def _records(rng, n=240, n_users=10, d_global=5, d_user=3):
    """GLMix records with SKEWED per-user counts so the RE dataset lands
    in MULTIPLE capacity-class buckets (the per-bucket readback hazard
    the discipline test guards against)."""
    w_global = np.linspace(-1, 1, d_global)
    w_user = rng.normal(size=(n_users, d_user)).astype(np.float32)
    # user 0 takes half the rows; the rest spread thin -> >= 2 cap classes
    users = np.concatenate([
        np.zeros(n // 2, np.int64),
        rng.integers(1, n_users, size=n - n // 2),
    ])
    recs = []
    for i in range(n):
        u = int(users[i])
        xg = rng.normal(size=d_global).astype(np.float32)
        xu = rng.normal(size=d_user).astype(np.float32)
        z = float(xg @ w_global + xu @ w_user[u])
        y = float(1 / (1 + np.exp(-z)) > rng.uniform())
        recs.append({
            "uid": f"r{i}",
            "response": y,
            "userId": f"user{u:03d}",
            "features": [
                {"name": f"g{j}", "term": "", "value": float(xg[j])}
                for j in range(d_global)
            ],
            "userFeatures": [
                {"name": f"u{j}", "term": "", "value": float(xu[j])}
                for j in range(d_user)
            ],
        })
    return recs


def _cd(rng, checkpointer=None):
    recs = _records(rng)
    ds = build_game_dataset(recs, SHARDS, ["userId"])
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfiguration("userId", "userShard")
    )
    coords = {
        "global": FixedEffectCoordinate(
            name="global",
            dataset=ds,
            problem=create_glm_problem(
                TaskType.LOGISTIC_REGRESSION, ds.shards["globalShard"].dim,
                config=OptimizerConfig(max_iter=20),
                regularization=RegularizationContext(RegularizationType.L2),
            ),
            feature_shard_id="globalShard",
            reg_weight=0.1,
        ),
        "per-user": RandomEffectCoordinate(
            name="per-user",
            dataset=ds,
            re_dataset=red,
            problem=RandomEffectOptimizationProblem(
                LOGISTIC,
                OptimizerConfig(max_iter=20),
                RegularizationContext(RegularizationType.L2),
                reg_weight=1.0,
            ),
        ),
    }
    assert len(red.buckets) >= 2, "need multiple buckets for the test"
    return CoordinateDescent(
        coords, ds, TaskType.LOGISTIC_REGRESSION,
        checkpointer=checkpointer,
    )


class TestDeferred:
    def test_fetch_all_is_one_readback(self):
        with overlap.overlap_scope(True):
            ds = [
                overlap.Deferred(jnp.float32(i), float) for i in range(5)
            ]
            overlap.reset_readback_stats()
            overlap.fetch_all(ds)
            assert overlap.readback_stats() == 1
            assert [d.result() for d in ds] == [0.0, 1.0, 2.0, 3.0, 4.0]
            # already-fetched deferreds never refetch
            overlap.fetch_all(ds)
            assert overlap.readback_stats() == 1

    def test_unfetched_deferred_forces_itself(self):
        with overlap.overlap_scope(True):
            d = overlap.Deferred(jnp.float32(7.0), float)
            overlap.reset_readback_stats()
            assert d.result() == 7.0
            assert overlap.readback_stats() == 1

    def test_overlap_off_fetches_eagerly(self):
        with overlap.overlap_scope(False):
            overlap.reset_readback_stats()
            d = overlap.Deferred(jnp.float32(3.0), float)
            assert overlap.readback_stats() == 1  # eager, serial order
            assert d.done and d.result() == 3.0

    def test_submit_inline_when_off(self):
        with overlap.overlap_scope(False):
            seen = []
            fut = overlap.submit(seen.append, 1)
            assert seen == [1]  # ran before submit returned
            overlap.wait(fut)

    def test_submit_io_failure_surfaces_at_drain(self, monkeypatch):
        # retries exhaust quickly so the test doesn't sleep through the
        # io_worker backoff schedule
        monkeypatch.setenv("PHOTON_RETRY_ATTEMPTS", "1")
        monkeypatch.setenv("PHOTON_RETRY_BASE_S", "0.001")

        def boom():
            raise OSError("disk gone")

        with overlap.overlap_scope(True):
            overlap.submit_io(boom, artifact="scores/part-00007.avro")
            # the failure re-raises at the drain barrier NAMING the
            # artifact (round-11 reliability contract) with the original
            # error chained underneath
            with pytest.raises(
                RuntimeError, match="scores/part-00007.avro"
            ) as ei:
                overlap.drain_io()
            # chain: RuntimeError -> SeamFailure (retry budget) -> the
            # original OSError
            assert "disk gone" in str(ei.value.__cause__.__cause__)
            overlap.drain_io()  # failure is consumed, barrier is clean

    def test_submit_io_failure_does_not_block_later_writes(
        self, monkeypatch, tmp_path
    ):
        monkeypatch.setenv("PHOTON_RETRY_ATTEMPTS", "1")
        monkeypatch.setenv("PHOTON_RETRY_BASE_S", "0.001")

        def boom():
            raise OSError("disk gone")

        ok = tmp_path / "later.txt"
        with overlap.overlap_scope(True):
            overlap.submit_io(boom, artifact="first")
            overlap.submit_io(ok.write_text, "landed", artifact="second")
            with pytest.raises(RuntimeError, match="first"):
                overlap.drain_io()
        # the write QUEUED BEHIND the failure still drained to disk
        assert ok.read_text() == "landed"


class TestReadbackDiscipline:
    def test_one_batched_readback_per_cd_iteration(self, rng):
        """The regression gate against overlap rot: a GAME CD iteration
        (FE + multi-bucket RE, trackers + objective + reg terms) performs
        EXACTLY ONE device_get — not one per bucket, not one per
        coordinate."""
        with overlap.overlap_scope(True):
            cd = _cd(rng)
            overlap.reset_readback_stats()
            result = cd.run(num_iterations=3)
            assert overlap.readback_stats() == 3
        assert len(result.objective_history) == 3
        # tracker facades were batch-fetched: reading them adds nothing
        before = overlap.readback_stats()
        t = result.trackers["per-user"][-1]
        assert t.num_entities == 10
        assert overlap.readback_stats() == before

    def test_serial_mode_reads_back_more(self, rng):
        """The serial path pulls per-bank + per-objective scalars — the
        cost the overlap layer exists to remove. Guards against the seam
        silently bypassing overlap.device_get."""
        with overlap.overlap_scope(False):
            cd = _cd(rng)
            overlap.reset_readback_stats()
            cd.run(num_iterations=1)
            assert overlap.readback_stats() >= 2  # tracker + objective

    def test_overlap_equals_serial(self, rng):
        """overlap == serial parity: identical objective history, model
        coefficients and tracker aggregates either way."""
        results = {}
        for label, enabled in (("overlap", True), ("serial", False)):
            with overlap.overlap_scope(enabled):
                r = _cd(np.random.default_rng(7)).run(num_iterations=2)
            results[label] = r
        np.testing.assert_allclose(
            results["overlap"].objective_history,
            results["serial"].objective_history,
            rtol=1e-6,
        )
        for name in ("global",):
            np.testing.assert_array_equal(
                np.asarray(results["overlap"].model.get_model(name).model.means),
                np.asarray(results["serial"].model.get_model(name).model.means),
            )
        np.testing.assert_array_equal(
            np.asarray(results["overlap"].model.get_model("per-user").bank),
            np.asarray(results["serial"].model.get_model("per-user").bank),
        )
        for a, b in zip(
            results["overlap"].trackers["per-user"],
            results["serial"].trackers["per-user"],
        ):
            assert a.num_entities == b.num_entities
            assert a.iterations_max == b.iterations_max
            assert a.reason_counts == b.reason_counts


class TestAsyncCheckpointIO:
    def test_checkpoints_on_disk_after_run(self, rng, tmp_path):
        from photon_ml_tpu.utils.checkpoint import TrainingCheckpointer

        with overlap.overlap_scope(True):
            ckpt = TrainingCheckpointer(str(tmp_path / "ckpt"))
            try:
                cd = _cd(rng, checkpointer=ckpt)
                cd.run(num_iterations=2)
                # run() drained: the latest step is durable NOW
                assert ckpt.latest_step() == 2
            finally:
                ckpt.close()


class TestPipelinedStaging:
    def test_pipelined_chunks_equal_serial(self, tmp_path, rng):
        """reader->decode->stage pipeline parity: chunk-for-chunk
        identical staging to the serial path."""
        from photon_ml_tpu.io import schemas
        from photon_ml_tpu.io.avro_codec import write_container
        from photon_ml_tpu.io.input_format import AvroInputDataFormat
        from photon_ml_tpu.io.streaming import iter_chunks, scan_stream

        for fi in range(3):
            recs = []
            for i in range(57):
                ix = rng.choice(40, size=6, replace=False)
                vs = rng.normal(size=6)
                recs.append({
                    "uid": f"{fi}-{i}",
                    "label": float(rng.uniform() > 0.5),
                    "features": [
                        {"name": str(int(j)), "term": "", "value": float(v)}
                        for j, v in zip(ix, vs)
                    ],
                    "offset": 0.0,
                    "weight": 1.0,
                })
            write_container(
                str(tmp_path / f"p{fi}.avro"),
                schemas.TRAINING_EXAMPLE_AVRO, recs,
            )
        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        kw = dict(rows_per_chunk=32, nnz_width=stats.max_nnz)
        serial = list(
            iter_chunks([str(tmp_path)], fmt, index_map, pipeline=False, **kw)
        )
        piped = list(
            iter_chunks([str(tmp_path)], fmt, index_map, pipeline=True, **kw)
        )
        assert len(serial) == len(piped) >= 2
        for a, b in zip(serial, piped):
            for fa, fb in zip(a, b):
                np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
