"""I/O tests: Avro codec round-trips (null+deflate), TrainingExampleAvro
parity fields, LibSVM parsing, index maps, constraints, feature stats,
validators.
"""

import numpy as np
import pytest


from photon_ml_tpu.data.stats import compute_summary
from photon_ml_tpu.data.validators import (
    DataValidationError,
    DataValidationType,
    sanity_check_data,
)
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import (
    read_avro_records,
    read_container,
    write_container,
)
from photon_ml_tpu.io.input_format import (
    AvroInputDataFormat,
    LibSVMInputDataFormat,
    parse_constraint_string,
)
from photon_ml_tpu.io.libsvm import parse_libsvm_line
from photon_ml_tpu.data.batch import make_dense_batch, make_sparse_batch
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.utils.index_map import (
    IdentityIndexMap,
    IndexMap,
    feature_key,
    intercept_key,
)


def example_records(n=10):
    recs = []
    for i in range(n):
        recs.append(
            {
                "uid": f"uid{i}",
                "label": float(i % 2),
                "features": [
                    {"name": f"f{j}", "term": "t", "value": float(j) + 0.5}
                    for j in range(1 + i % 3)
                ],
                "metadataMap": {"q": str(i // 2)},
                "weight": 1.0 + 0.1 * i,
                "offset": 0.01 * i,
            }
        )
    return recs


class TestAvroCodec:
    @pytest.mark.parametrize("codec", ["null", "deflate"])
    def test_roundtrip_training_examples(self, tmp_path, codec):
        path = str(tmp_path / "data.avro")
        recs = example_records()
        n = write_container(path, schemas.TRAINING_EXAMPLE_AVRO, recs, codec=codec)
        assert n == len(recs)
        _, it = read_container(path)
        got = list(it)
        assert got == recs

    def test_roundtrip_all_schemas(self, tmp_path):
        cases = [
            (schemas.BAYESIAN_LINEAR_MODEL_AVRO, {
                "modelId": "global",
                "modelClass": "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
                "means": [{"name": "a", "term": "", "value": 1.5}],
                "variances": [{"name": "a", "term": "", "value": 0.1}],
                "lossFunction": None,
            }),
            (schemas.LATENT_FACTOR_AVRO, {
                "effectId": "user1", "latentFactor": [0.1, -0.2, 0.3],
            }),
            (schemas.SCORING_RESULT_AVRO, {
                "uid": None, "label": 1.0, "modelId": "m",
                "predictionScore": 0.75, "weight": None, "metadataMap": None,
            }),
            (schemas.FEATURE_SUMMARIZATION_RESULT_AVRO, {
                "featureName": "f", "featureTerm": "t",
                "metrics": {"mean": 0.5, "max": 2.0},
            }),
        ]
        for i, (schema, rec) in enumerate(cases):
            path = str(tmp_path / f"s{i}.avro")
            write_container(path, schema, [rec])
            _, it = read_container(path)
            assert list(it) == [rec]

    def test_multi_block_and_dir_read(self, tmp_path):
        d = tmp_path / "data"
        d.mkdir()
        recs = example_records(100)
        write_container(
            str(d / "part-0.avro"), schemas.TRAINING_EXAMPLE_AVRO, recs[:50],
            sync_interval=256,
        )
        write_container(
            str(d / "part-1.avro"), schemas.TRAINING_EXAMPLE_AVRO, recs[50:],
            sync_interval=256,
        )
        got = list(read_avro_records(str(d)))
        assert got == recs

    def test_negative_numbers_zigzag(self, tmp_path):
        schema = {
            "name": "T", "type": "record",
            "fields": [{"name": "x", "type": "long"}],
        }
        recs = [{"x": v} for v in [0, -1, 1, -2**40, 2**40, 63, -64]]
        path = str(tmp_path / "z.avro")
        write_container(path, schema, recs)
        _, it = read_container(path)
        assert list(it) == recs


class TestLibSVM:
    def test_parse(self):
        lab, pairs = parse_libsvm_line("-1 3:0.5 10:1.25 # comment")
        assert lab == 0.0
        assert pairs == [(2, 0.5), (9, 1.25)]
        assert parse_libsvm_line("# only comment") is None

    def test_load_builds_batch(self, tmp_path):
        p = tmp_path / "a1a.txt"
        p.write_text("+1 1:1 3:2\n-1 2:1\n+1 1:0.5 2:0.5 3:0.5\n")
        fmt = LibSVMInputDataFormat(add_intercept=True)
        data = fmt.load(str(p))
        assert data.num_features == 4  # 3 features + intercept
        assert data.intercept_index is not None
        lab = np.asarray(data.batch.labels)
        w = np.asarray(data.batch.weights)
        assert lab[np.where(w > 0)].tolist() == [1.0, 0.0, 1.0]


class TestAvroInput:
    def test_load(self, tmp_path):
        path = str(tmp_path / "train.avro")
        write_container(path, schemas.TRAINING_EXAMPLE_AVRO, example_records())
        fmt = AvroInputDataFormat(add_intercept=True)
        data = fmt.load(path)
        assert intercept_key() in data.index_map
        # f0..f2 with term t plus intercept
        assert data.num_features == 4
        w = np.asarray(data.batch.weights)
        real = w > 0
        assert real.sum() == 10
        np.testing.assert_allclose(
            np.asarray(data.batch.offsets)[real][:3], [0.0, 0.01, 0.02], atol=1e-6
        )

    def test_selected_features(self, tmp_path):
        path = str(tmp_path / "train.avro")
        write_container(path, schemas.TRAINING_EXAMPLE_AVRO, example_records())
        fmt = AvroInputDataFormat(
            add_intercept=False, selected_features=[feature_key("f0", "t")]
        )
        data = fmt.load(path)
        assert data.num_features == 1


class TestIndexMap:
    def test_build_deterministic(self):
        m1 = IndexMap.build(["b\t", "a\t", "b\t"], add_intercept=True)
        m2 = IndexMap.build(["a\t", "b\t"], add_intercept=True)
        assert dict(m1.items()) == dict(m2.items())
        assert m1.get_index("a\t") == 0
        assert m1.get_index(intercept_key()) == 2

    def test_reverse_lookup(self):
        m = IndexMap.build(["x\t1", "y\t2"])
        for k, i in m.items():
            assert m.get_feature_name(i) == k
        assert m.get_feature_name(99) is None
        assert m.get_index("missing\t") == -1

    def test_save_load(self, tmp_path):
        m = IndexMap.build(["x\t", "y\t"], add_intercept=True)
        p = str(tmp_path / "index" / "map.json")
        m.save(p)
        m2 = IndexMap.load(p)
        assert dict(m2.items()) == dict(m.items())

    def test_identity(self):
        m = IdentityIndexMap(5)
        assert m.get_index("3\t") == 3
        assert m.get_index(feature_key("7")) == -1
        assert m.get_feature_name(2) == feature_key("2")


class TestConstraints:
    def _imap(self):
        return IndexMap.build(
            [feature_key("a", ""), feature_key("b", "")], add_intercept=True
        )

    def test_explicit(self):
        im = self._imap()
        box = parse_constraint_string(
            '[{"name": "a", "term": "", "lowerBound": -1, "upperBound": 1}]',
            im, 3, im.get_index(intercept_key()),
        )
        lo = np.asarray(box.lower)
        ia = im.get_index(feature_key("a", ""))
        assert lo[ia] == -1.0
        assert np.isinf(lo[im.get_index(feature_key("b", ""))])

    def test_wildcard_excludes_intercept(self):
        im = self._imap()
        box = parse_constraint_string(
            '[{"name": "*", "term": "*", "lowerBound": 0, "upperBound": 2}]',
            im, 3, im.get_index(intercept_key()),
        )
        icept = im.get_index(intercept_key())
        assert np.isinf(np.asarray(box.upper)[icept])
        others = [i for i in range(3) if i != icept]
        assert np.all(np.asarray(box.upper)[others] == 2.0)

    def test_conflicts_rejected(self):
        im = self._imap()
        with pytest.raises(ValueError):
            parse_constraint_string(
                '[{"name": "*", "term": "*", "lowerBound": 0, "upperBound": 2},'
                ' {"name": "a", "term": "", "lowerBound": 0, "upperBound": 1}]',
                im, 3, None,
            )
        with pytest.raises(ValueError):
            parse_constraint_string(
                '[{"name": "a", "term": "", "lowerBound": 5, "upperBound": 1}]',
                im, 3, None,
            )


class TestStats:
    def test_dense_matches_numpy(self, rng):
        x = rng.normal(size=(50, 4)).astype(np.float32)
        batch = make_dense_batch(x, np.zeros(50))
        s = compute_summary(batch, 4)
        np.testing.assert_allclose(np.asarray(s.mean), x.mean(0), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(s.variance), x.var(0, ddof=1), rtol=1e-4
        )
        assert float(s.count) == 50
        np.testing.assert_allclose(np.asarray(s.max), x.max(0), atol=1e-6)
        np.testing.assert_allclose(np.asarray(s.min), x.min(0), atol=1e-6)

    def test_sparse_implicit_zeros(self):
        # 3 rows, dim 3: feature 0 appears twice (values 2, -1), feature 1
        # once (value 3), feature 2 never.
        batch = make_sparse_batch(
            [([0], [2.0]), ([0, 1], [-1.0, 3.0]), ([1], [0.0])],
            [0.0, 0.0, 0.0],
        )
        # NOTE row 3's explicit 0.0 for feature 1 counts as a slot but has
        # value 0 → not a nonzero.
        s = compute_summary(batch, 3)
        np.testing.assert_allclose(np.asarray(s.mean), [1 / 3, 1.0, 0.0], atol=1e-6)
        assert np.asarray(s.num_nonzeros).tolist() == [2.0, 1.0, 0.0]
        np.testing.assert_allclose(np.asarray(s.max), [2.0, 3.0, 0.0])
        np.testing.assert_allclose(np.asarray(s.min), [-1.0, 0.0, 0.0])


class TestValidators:
    def test_clean_passes(self, rng):
        x = rng.normal(size=(16, 3)).astype(np.float32)
        y = (rng.uniform(size=16) > 0.5).astype(np.float32)
        sanity_check_data(make_dense_batch(x, y), TaskType.LOGISTIC_REGRESSION)

    def test_nonbinary_labels_fail_classification(self, rng):
        x = rng.normal(size=(8, 3)).astype(np.float32)
        y = np.array([0, 1, 2, 0, 1, 0, 1, 0], np.float32)
        with pytest.raises(DataValidationError, match="labels_binary"):
            sanity_check_data(make_dense_batch(x, y), TaskType.LOGISTIC_REGRESSION)

    def test_negative_labels_fail_poisson(self, rng):
        x = rng.normal(size=(8, 3)).astype(np.float32)
        y = np.array([1, -1, 2, 0, 1, 0, 1, 0], np.float32)
        with pytest.raises(DataValidationError, match="labels_non_negative"):
            sanity_check_data(make_dense_batch(x, y), TaskType.POISSON_REGRESSION)

    def test_nan_features_fail(self):
        x = np.array([[1.0, np.nan], [0.0, 1.0]], np.float32)
        with pytest.raises(DataValidationError, match="features_finite"):
            sanity_check_data(
                make_dense_batch(x, [0.0, 1.0]), TaskType.LINEAR_REGRESSION
            )

    def test_disabled_skips(self):
        x = np.array([[np.nan]], np.float32)
        sanity_check_data(
            make_dense_batch(x, [0.0]),
            TaskType.LINEAR_REGRESSION,
            DataValidationType.VALIDATE_DISABLED,
        )
