"""Streaming (>RAM) GLM input path: exact full-batch equivalence with the
in-memory trainer, fixed-shape chunking, and bounded-RSS behavior."""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.io.input_format import AvroInputDataFormat
from photon_ml_tpu.io.streaming import (
    StreamingGLMObjective,
    iter_chunks,
    scan_stream,
)
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.training import (
    train_generalized_linear_model,
    train_streaming_glm,
)


def _write_files(tmp_path, rng, n_files=3, rows_per_file=80, d=25, k=4):
    w_true = rng.normal(size=d)
    for fi in range(n_files):
        recs = []
        for i in range(rows_per_file):
            ix = rng.choice(d, size=k, replace=False)
            vs = rng.normal(size=k)
            z = float(w_true[ix] @ vs)
            recs.append({
                "uid": f"f{fi}-r{i}",
                "label": float(rng.uniform() < 1 / (1 + np.exp(-z))),
                "features": [
                    {"name": f"x{j}", "term": "", "value": float(v)}
                    for j, v in zip(ix, vs)
                ],
                "offset": 0.0,
                "weight": 1.0,
            })
        write_container(
            str(tmp_path / f"part-{fi}.avro"),
            schemas.TRAINING_EXAMPLE_AVRO,
            recs,
        )
    return tmp_path


class TestStreamingChunks:
    def test_fixed_shape_and_coverage(self, tmp_path, rng):
        _write_files(tmp_path, rng)
        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        assert stats.num_rows == 240
        chunks = list(iter_chunks(
            [str(tmp_path)], fmt, index_map,
            rows_per_chunk=100, nnz_width=stats.max_nnz,
        ))
        assert len(chunks) == 3  # 240 rows / 100
        for c in chunks:
            assert c.indices.shape == (100, stats.max_nnz)
        total_real = sum(int((c.weights > 0).sum()) for c in chunks)
        assert total_real == 240

    def test_scan_python_fallback_builds_vocabulary(
        self, tmp_path, rng, monkeypatch
    ):
        # the Python-codec fallback must collect feature keys too — an
        # empty vocabulary would silently fit an intercept-only model
        _write_files(tmp_path, rng, n_files=1)
        import photon_ml_tpu.io.native_avro as na

        monkeypatch.setattr(na, "available", lambda: False)
        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        assert index_map.size == 26  # 25 features + intercept
        assert stats.num_rows == 80
        assert stats.max_nnz == 5  # 4 features + intercept

    def test_streaming_objective_matches_in_memory(self, tmp_path, rng):
        _write_files(tmp_path, rng)
        fmt = AvroInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        obj = StreamingGLMObjective(
            [str(tmp_path)], fmt, index_map, stats,
            TaskType.LOGISTIC_REGRESSION, rows_per_chunk=64,
        )
        from photon_ml_tpu.ops.losses import LOGISTIC
        from photon_ml_tpu.ops.objective import GLMObjective

        oracle = GLMObjective(LOGISTIC, loaded.num_features)
        w = jnp.asarray(rng.normal(size=loaded.num_features).astype(np.float32))
        v_s, g_s = obj.value_and_gradient(w, 0.4)
        v_m, g_m = oracle.value_and_gradient(w, loaded.batch, 0.4)
        np.testing.assert_allclose(float(v_s), float(v_m), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g_s), np.asarray(g_m), rtol=1e-4, atol=1e-5
        )


class TestStreamingTraining:
    def test_matches_in_memory_lbfgs(self, tmp_path, rng):
        _write_files(tmp_path, rng, n_files=4, rows_per_file=100)
        fmt = AvroInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        models_s, results_s, imap = train_streaming_glm(
            [str(tmp_path)], TaskType.LOGISTIC_REGRESSION,
            regularization_type=__import__(
                "photon_ml_tpu.optim.config", fromlist=["RegularizationType"]
            ).RegularizationType.L2,
            regularization_weights=[1.0, 0.1],
            max_iter=40,
            rows_per_chunk=128,
        )
        from photon_ml_tpu.optim.config import RegularizationType

        models_m, results_m = train_generalized_linear_model(
            loaded.batch, TaskType.LOGISTIC_REGRESSION, loaded.num_features,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0, 0.1],
            max_iter=40,
        )
        for lam in (1.0, 0.1):
            np.testing.assert_allclose(
                np.asarray(models_s[lam].coefficients.means),
                np.asarray(models_m[lam].coefficients.means),
                atol=5e-3,
            )

    def test_elastic_net_matches_in_memory_owlqn(self, tmp_path, rng):
        """Streaming elastic-net (host-driven OWL-QN, round 4): same
        iterate rules as the in-memory OWL-QN, so the fitted coefficients
        agree; the L1 path actually sparsifies."""
        from photon_ml_tpu.optim.config import RegularizationType

        _write_files(tmp_path, rng, n_files=4, rows_per_file=100)
        fmt = AvroInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        models_s, results_s, _ = train_streaming_glm(
            [str(tmp_path)], TaskType.LOGISTIC_REGRESSION,
            regularization_type=RegularizationType.ELASTIC_NET,
            elastic_net_alpha=0.5,
            regularization_weights=[1.0],
            max_iter=60,
            rows_per_chunk=128,
        )
        models_m, results_m = train_generalized_linear_model(
            loaded.batch, TaskType.LOGISTIC_REGRESSION, loaded.num_features,
            regularization_type=RegularizationType.ELASTIC_NET,
            elastic_net_alpha=0.5,
            regularization_weights=[1.0],
            max_iter=60,
        )
        # both stop on the same tolerance rules near a flat optimum: pin
        # the OBJECTIVE tightly, the coefficients loosely
        np.testing.assert_allclose(
            float(results_s[1.0].value), float(results_m[1.0].value),
            rtol=1e-4,
        )
        np.testing.assert_allclose(
            np.asarray(models_s[1.0].coefficients.means),
            np.asarray(models_m[1.0].coefficients.means),
            atol=2e-2,
        )

    def test_pure_l1_sparsifies(self, tmp_path, rng):
        from photon_ml_tpu.optim.config import RegularizationType

        _write_files(tmp_path, rng, n_files=2, rows_per_file=100)
        models, results, _ = train_streaming_glm(
            [str(tmp_path)], TaskType.LOGISTIC_REGRESSION,
            regularization_type=RegularizationType.L1,
            regularization_weights=[5.0],
            max_iter=40,
            rows_per_chunk=128,
        )
        w = np.asarray(models[5.0].coefficients.means)
        assert (w == 0).sum() > 0  # a strong L1 zeroes some coefficients
        assert np.isfinite(float(results[5.0].value))


class TestChunkCache:
    def test_eval2_skips_decode_and_matches(self, tmp_path, rng, monkeypatch):
        """persist(MEMORY_AND_DISK) semantics: the first evaluation
        populates the staged-chunk cache, the second decodes NOTHING and
        returns the identical (value, gradient)."""
        import photon_ml_tpu.io.streaming as streaming_mod

        _write_files(tmp_path, rng)
        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        obj = StreamingGLMObjective(
            [str(tmp_path)], fmt, index_map, stats,
            TaskType.LOGISTIC_REGRESSION, rows_per_chunk=64,
        )
        calls = {"n": 0}
        # count at the DECODE seam: the overlap pipeline stages rows via
        # decode_payload/stream_rows_from_payload on a worker thread, and
        # the serial stream_rows routes through the same decode_payload
        real = AvroInputDataFormat.decode_payload

        def counting(self, path):
            calls["n"] += 1
            return real(self, path)

        monkeypatch.setattr(AvroInputDataFormat, "decode_payload", counting)
        w = jnp.asarray(rng.normal(size=obj.dim).astype(np.float32))
        v1, g1 = obj.value_and_gradient(w, 0.1)
        decodes_after_first = calls["n"]
        assert decodes_after_first == 3  # one per file
        v2, g2 = obj.value_and_gradient(w, 0.1)
        assert calls["n"] == decodes_after_first  # cache hit: zero decodes
        assert float(v1) == float(v2)
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))

    def test_disk_spill_tier_exact(self, tmp_path, rng):
        """A cache budget smaller than the dataset spills staged chunks to
        scratch; evaluation 2 (memory tier + spill tier) still matches."""
        _write_files(tmp_path, rng)
        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        obj = StreamingGLMObjective(
            [str(tmp_path)], fmt, index_map, stats,
            TaskType.LOGISTIC_REGRESSION, rows_per_chunk=64,
            cache_bytes=1,  # forces budget=1 chunk in memory, rest spilled
        )
        w = jnp.asarray(rng.normal(size=obj.dim).astype(np.float32))
        v1, g1 = obj.value_and_gradient(w, 0.0)
        assert obj._disk_cache is not None and obj._disk_cache.count >= 1
        spill_dir = obj._disk_cache.dir
        assert os.path.isdir(spill_dir)
        v2, g2 = obj.value_and_gradient(w, 0.0)
        assert float(v1) == float(v2)
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        # scratch dies with the objective
        obj._disk_cache.close()
        assert not os.path.isdir(spill_dir)

    def test_cache_disabled_streams_every_eval(self, tmp_path, rng, monkeypatch):
        import photon_ml_tpu.io.streaming as streaming_mod

        _write_files(tmp_path, rng)
        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        obj = StreamingGLMObjective(
            [str(tmp_path)], fmt, index_map, stats,
            TaskType.LOGISTIC_REGRESSION, rows_per_chunk=64,
            cache_bytes=0,
        )
        calls = {"n": 0}
        # count at the DECODE seam: the overlap pipeline stages rows via
        # decode_payload/stream_rows_from_payload on a worker thread, and
        # the serial stream_rows routes through the same decode_payload
        real = AvroInputDataFormat.decode_payload

        def counting(self, path):
            calls["n"] += 1
            return real(self, path)

        monkeypatch.setattr(AvroInputDataFormat, "decode_payload", counting)
        w = jnp.zeros((obj.dim,), jnp.float32)
        obj.value_and_gradient(w)
        obj.value_and_gradient(w)
        assert calls["n"] == 6  # 3 files x 2 evaluations


@pytest.mark.slow
class TestBoundedMemory:
    def test_rss_bounded_by_chunk_not_dataset(self, tmp_path):
        """Stream a dataset whose in-memory record form is far larger than
        the streaming working set; assert the RSS growth during streaming
        evaluations stays bounded by ~a file + chunk, not the dataset."""
        script = r"""
import os, resource, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.io.input_format import AvroInputDataFormat
from photon_ml_tpu.io.streaming import StreamingGLMObjective, scan_stream
from photon_ml_tpu.task import TaskType

tmp = sys.argv[1]
rng = np.random.default_rng(0)
n_files, rows, k, d = 8, 60_000, 16, 4000
for fi in range(n_files):
    ix = rng.integers(0, d, size=(rows, k))
    vs = rng.normal(size=(rows, k)).astype(np.float32)
    lab = (rng.uniform(size=rows) > 0.5).astype(np.float64)
    recs = [
        {
            "uid": str(i),
            "label": float(lab[i]),
            "features": [
                {"name": f"x{j}", "term": "", "value": float(v)}
                for j, v in zip(ix[i], vs[i])
            ],
            "offset": 0.0,
            "weight": 1.0,
        }
        for i in range(rows)
    ]
    write_container(
        os.path.join(tmp, f"part-{fi}.avro"),
        schemas.TRAINING_EXAMPLE_AVRO, recs,
    )
    del recs

fmt = AvroInputDataFormat()
# base BEFORE the scan: the vocabulary pass must be file-bounded too
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
index_map, stats = scan_stream([tmp], fmt)
obj = StreamingGLMObjective(
    [tmp], fmt, index_map, stats, TaskType.LOGISTIC_REGRESSION,
    rows_per_chunk=32768,
)
w = jnp.zeros((obj.dim,), jnp.float32)
for _ in range(3):
    obj.value_and_gradient(w, 0.1)
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print("DELTA_KB", peak - base)
"""
        out = subprocess.run(
            [sys.executable, "-c", script, str(tmp_path)],
            capture_output=True, text=True, timeout=540,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert out.returncode == 0, out.stderr[-2000:]
        delta_kb = int(out.stdout.split("DELTA_KB")[-1].strip())
        # 480k rows x 16 nnz as python record dicts is >1 GB; the steady
        # streaming passes must not grow RSS by more than ~a decoded file
        assert delta_kb < 200_000, delta_kb


class TestStreamingTiledKernel:
    """Cached evaluations on the FAST tiled kernel: staged chunks have
    fixed structure after the populate pass, so per-chunk tile schedules
    are built once and evaluation 2..N runs the Pallas bilinear kernels
    (interpret mode on CPU) — values must match the scatter path exactly
    (bf16x2w kernel noise only)."""

    def test_cached_tiled_eval_matches_scatter(self, tmp_path, rng):
        from photon_ml_tpu.ops.tiled_sparse import TileParams

        _write_files(tmp_path, rng, n_files=3, rows_per_file=80)
        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        make = lambda kernel: StreamingGLMObjective(
            [str(tmp_path)], fmt, index_map, stats,
            TaskType.LOGISTIC_REGRESSION, rows_per_chunk=64,
            kernel=kernel,
            tile_params=TileParams(s_hi=8, s_lo=8, chunk=32),
        )
        tiled = make("tiled")
        scatter = make("scatter")
        w = jnp.asarray(
            rng.normal(size=index_map.size).astype(np.float32) * 0.1
        )
        # eval 1 populates the cache on BOTH objectives (scatter partial)
        v1_t, g1_t = tiled.value_and_gradient(w, 0.3)
        v1_s, g1_s = scatter.value_and_gradient(w, 0.3)
        np.testing.assert_allclose(float(v1_t), float(v1_s), rtol=1e-5)
        # eval 2: tiled objective switches to the per-chunk schedules
        v2_t, g2_t = tiled.value_and_gradient(w, 0.3)
        assert tiled._tiled_chunk_count == 4  # 240 rows / 64 per chunk
        v2_s, g2_s = scatter.value_and_gradient(w, 0.3)
        np.testing.assert_allclose(float(v2_t), float(v2_s), rtol=2e-4)
        np.testing.assert_allclose(
            np.asarray(g2_t), np.asarray(g2_s), rtol=2e-3, atol=2e-4
        )

    def test_tiled_budget_overflow_falls_back(self, tmp_path, rng):
        from photon_ml_tpu.ops.tiled_sparse import TileParams

        _write_files(tmp_path, rng, n_files=2, rows_per_file=80)
        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        obj = StreamingGLMObjective(
            [str(tmp_path)], fmt, index_map, stats,
            TaskType.LOGISTIC_REGRESSION, rows_per_chunk=64,
            kernel="tiled",
            tile_params=TileParams(s_hi=8, s_lo=8, chunk=32),
            # budget fits roughly one chunk's schedules: the rest must
            # evaluate on the scatter partial, with identical totals
            tiled_cache_bytes=30_000,
        )
        w = jnp.asarray(
            rng.normal(size=index_map.size).astype(np.float32) * 0.1
        )
        v1, _ = obj.value_and_gradient(w, 0.2)
        v2, g2 = obj.value_and_gradient(w, 0.2)
        assert 0 < obj._tiled_chunk_count < 3
        np.testing.assert_allclose(float(v2), float(v1), rtol=2e-4)

    def test_streaming_elastic_net_on_tiled_cache(self, tmp_path, rng):
        """Elastic-net (host OWL-QN) rides the tiled cached path too —
        the full streaming training entry point with kernel='tiled'."""
        from photon_ml_tpu.optim import RegularizationType

        _write_files(tmp_path, rng, n_files=3, rows_per_file=80)
        fmt = AvroInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        d = loaded.num_features
        models_mem, _ = train_generalized_linear_model(
            loaded.batch, TaskType.LOGISTIC_REGRESSION, d,
            regularization_type=RegularizationType.ELASTIC_NET,
            elastic_net_alpha=0.5, regularization_weights=[0.1],
            max_iter=30, intercept_index=loaded.intercept_index,
            kernel="scatter",
        )
        from photon_ml_tpu.ops.tiled_sparse import TileParams

        models_st, _, _ = train_streaming_glm(
            [str(tmp_path)], TaskType.LOGISTIC_REGRESSION,
            regularization_type=RegularizationType.ELASTIC_NET,
            elastic_net_alpha=0.5, regularization_weights=[0.1],
            max_iter=30, rows_per_chunk=64,
            kernel="tiled",
            tile_params=TileParams(s_hi=8, s_lo=8, chunk=32),
        )
        np.testing.assert_allclose(
            np.asarray(models_st[0.1].means),
            np.asarray(models_mem[0.1].means),
            atol=5e-3,
        )


class TestStreamingStageParity:
    """Round 5: every driver stage is a bounded-memory pass over staged
    chunks, matching the reference's everything-is-an-RDD-pass design
    (Driver.scala:525-552; HessianVectorAggregator.scala:137-152)."""

    def test_streamed_tron_matches_in_memory(self, tmp_path, rng):
        from photon_ml_tpu.optim import OptimizerType, RegularizationType

        _write_files(tmp_path, rng, n_files=3, rows_per_file=80)
        fmt = AvroInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        d = loaded.num_features
        m_mem, r_mem = train_generalized_linear_model(
            loaded.batch, TaskType.LOGISTIC_REGRESSION, d,
            optimizer_type=OptimizerType.TRON,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0], kernel="scatter",
        )
        m_st, r_st, _ = train_streaming_glm(
            [str(tmp_path)], TaskType.LOGISTIC_REGRESSION,
            optimizer_type=OptimizerType.TRON,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0], rows_per_chunk=64,
            kernel="scatter",
        )
        np.testing.assert_allclose(
            np.asarray(m_st[1.0].means), np.asarray(m_mem[1.0].means),
            atol=5e-3,
        )

    def test_streamed_hessian_vector_matches_in_memory(self, tmp_path, rng):
        from photon_ml_tpu.io.streaming import StreamingGLMObjective
        from photon_ml_tpu.ops.losses import LOGISTIC
        from photon_ml_tpu.ops.objective import GLMObjective

        _write_files(tmp_path, rng)
        fmt = AvroInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        obj = StreamingGLMObjective(
            [str(tmp_path)], fmt, index_map, stats,
            TaskType.LOGISTIC_REGRESSION, rows_per_chunk=64,
            kernel="scatter",
        )
        oracle = GLMObjective(LOGISTIC, loaded.num_features)
        w = jnp.asarray(
            rng.normal(size=loaded.num_features).astype(np.float32)
        )
        dv = jnp.asarray(
            rng.normal(size=loaded.num_features).astype(np.float32)
        )
        hv_s = obj.hessian_vector(w, dv, 0.3)
        hv_m = oracle.hessian_vector(w, dv, loaded.batch, 0.3)
        np.testing.assert_allclose(
            np.asarray(hv_s), np.asarray(hv_m), rtol=1e-4, atol=1e-5
        )
        hd_s = obj.hessian_diagonal(w, 0.3)
        hd_m = oracle.hessian_diagonal(w, loaded.batch, 0.3)
        np.testing.assert_allclose(
            np.asarray(hd_s), np.asarray(hd_m), rtol=1e-4, atol=1e-5
        )

    def test_streamed_summary_matches_in_memory(self, tmp_path, rng):
        from photon_ml_tpu.data.stats import compute_summary
        from photon_ml_tpu.io.streaming import streaming_summary

        _write_files(tmp_path, rng)
        fmt = AvroInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        mem = compute_summary(loaded.batch, loaded.num_features)
        st, sample = streaming_summary(
            [str(tmp_path)], fmt, index_map, stats, rows_per_chunk=64,
            reservoir_rows=50,
        )
        for f in ("mean", "variance", "num_nonzeros", "max", "min",
                  "norm_l1", "mean_abs"):
            np.testing.assert_allclose(
                np.asarray(getattr(st, f)), np.asarray(getattr(mem, f)),
                rtol=1e-4, atol=1e-5, err_msg=f,
            )
        assert int(st.count) == int(mem.count)
        assert sample.indices.shape[0] == 50
        assert bool((sample.weights > 0).all())

    def test_streamed_normalization_and_variances(self, tmp_path, rng):
        from photon_ml_tpu.data.stats import compute_summary
        from photon_ml_tpu.io.streaming import streaming_summary
        from photon_ml_tpu.ops.normalization import (
            NormalizationType,
            build_normalization,
        )
        from photon_ml_tpu.optim import RegularizationType

        _write_files(tmp_path, rng, n_files=3, rows_per_file=80)
        fmt = AvroInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        d = loaded.num_features
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        st, _ = streaming_summary(
            [str(tmp_path)], fmt, index_map, stats, rows_per_chunk=64
        )
        norm = build_normalization(
            NormalizationType.STANDARDIZATION,
            mean=st.mean, std=st.std, max_magnitude=st.max_magnitude,
            intercept_index=loaded.intercept_index,
        )
        m_mem, _ = train_generalized_linear_model(
            loaded.batch, TaskType.LOGISTIC_REGRESSION, d,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0], normalization=norm,
            compute_variances=True,
            intercept_index=loaded.intercept_index, kernel="scatter",
        )
        m_st, _, _ = train_streaming_glm(
            [str(tmp_path)], TaskType.LOGISTIC_REGRESSION,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0], rows_per_chunk=64,
            normalization=norm, compute_variances=True, kernel="scatter",
        )
        np.testing.assert_allclose(
            np.asarray(m_st[1.0].means), np.asarray(m_mem[1.0].means),
            atol=5e-3,
        )
        np.testing.assert_allclose(
            np.asarray(m_st[1.0].coefficients.variances),
            np.asarray(m_mem[1.0].coefficients.variances),
            rtol=5e-3,
        )

    def test_streaming_driver_full_stage_parity(self, tmp_path, rng):
        """--streaming with normalization + variances + summarization +
        diagnostics + validate-per-iteration, end to end through the
        driver: all previously-guarded stages run in bounded memory."""
        from photon_ml_tpu.cli.glm_driver import (
            DiagnosticMode,
            GLMDriver,
            GLMParams,
        )
        from photon_ml_tpu.ops.normalization import NormalizationType

        train = tmp_path / "train"; train.mkdir()
        val = tmp_path / "val"; val.mkdir()
        _write_files(train, rng, n_files=3, rows_per_file=80)
        _write_files(val, rng, n_files=1, rows_per_file=80)
        params = GLMParams(
            train_dir=str(train),
            validate_dir=str(val),
            output_dir=str(tmp_path / "out"),
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[1.0],
            normalization_type=NormalizationType.STANDARDIZATION,
            compute_variances=True,
            summarization_output_dir=str(tmp_path / "summary"),
            diagnostic_mode=DiagnosticMode.ALL,
            validate_per_iteration=True,
            streaming=True,
            kernel="scatter",
        )
        driver = GLMDriver(params)
        driver.run()
        assert driver.best_model is not None
        assert driver.per_iteration_metrics[1.0]
        assert (tmp_path / "summary" / "part-00000.avro").exists()
        assert (
            tmp_path / "out" / "model-diagnostics" / "report.html"
        ).exists()


class TestLibSVMStreaming:
    """Round 5: the streaming protocol is format-generic — LibSVM text
    streams line-at-a-time through the same chunked path the reference
    gives both formats via GLMSuite (LibSVMInputDataFormat.scala:43-75)."""

    def _write_libsvm(self, tmp_path, rng, n_files=3, rows=70, d=20, k=4):
        w_true = rng.normal(size=d)
        for fi in range(n_files):
            lines = []
            for _ in range(rows):
                ix = np.sort(rng.choice(d, size=k, replace=False))
                vs = rng.normal(size=k)
                z = float(w_true[ix] @ vs)
                y = 1 if rng.uniform() < 1 / (1 + np.exp(-z)) else 0
                lines.append(
                    f"{y} " + " ".join(
                        f"{int(i) + 1}:{v:.6f}" for i, v in zip(ix, vs)
                    )
                )
            (tmp_path / f"part-{fi}.txt").write_text("\n".join(lines) + "\n")

    def test_libsvm_streaming_matches_in_memory(self, tmp_path, rng):
        from photon_ml_tpu.io.input_format import LibSVMInputDataFormat
        from photon_ml_tpu.optim import RegularizationType

        self._write_libsvm(tmp_path, rng)
        fmt = LibSVMInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        d = loaded.num_features
        m_mem, _ = train_generalized_linear_model(
            loaded.batch, TaskType.LOGISTIC_REGRESSION, d,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0], kernel="scatter",
        )
        m_st, r_st, imap = train_streaming_glm(
            [str(tmp_path)], TaskType.LOGISTIC_REGRESSION,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0], rows_per_chunk=64,
            kernel="scatter", fmt=fmt,
        )
        assert imap.size == d
        np.testing.assert_allclose(
            np.asarray(m_st[1.0].means), np.asarray(m_mem[1.0].means),
            atol=5e-3,
        )

    def test_libsvm_stream_scan_feature_dimension(self, tmp_path, rng):
        """Pre-declared --feature-dimension skips the vocabulary scan
        (identity map), exactly like the in-memory loader."""
        from photon_ml_tpu.io.input_format import LibSVMInputDataFormat

        self._write_libsvm(tmp_path, rng, n_files=1, rows=30, d=15)
        fmt = LibSVMInputDataFormat(feature_dimension=15)
        index_map, stats = scan_stream([str(tmp_path)], fmt)
        assert index_map.size == 16  # 15 + intercept
        assert stats.num_rows == 30
        chunks = list(iter_chunks(
            [str(tmp_path)], fmt, index_map,
            rows_per_chunk=16, nnz_width=stats.max_nnz,
        ))
        total = sum(int((c.weights > 0).sum()) for c in chunks)
        assert total == 30

    def test_libsvm_streaming_driver_end_to_end(self, tmp_path, rng):
        """--input-file-format LIBSVM --streaming true through the CLI
        driver matches the non-streaming run."""
        from photon_ml_tpu.cli.glm_driver import GLMDriver, GLMParams

        train = tmp_path / "train"; train.mkdir()
        val = tmp_path / "val"; val.mkdir()
        self._write_libsvm(train, rng)
        self._write_libsvm(val, rng, n_files=1)
        results = {}
        for streaming, out in ((True, "out_s"), (False, "out_m")):
            params = GLMParams(
                train_dir=str(train),
                validate_dir=str(val),
                output_dir=str(tmp_path / out),
                task=TaskType.LOGISTIC_REGRESSION,
                input_format="LIBSVM",
                regularization_weights=[1.0],
                streaming=streaming,
                kernel="scatter",
            )
            driver = GLMDriver(params)
            driver.run()
            results[streaming] = driver
        np.testing.assert_allclose(
            np.asarray(results[True].models[1.0].means),
            np.asarray(results[False].models[1.0].means),
            atol=5e-3,
        )


class TestFusedScanSummary:
    """One-pass scan + colStats (stream_scan_with_summary): identical
    vocabulary/stats to the classic scan, summary matching the in-memory
    colStats — the fused form of the preprocess stage's back-to-back
    scan_stream + streaming_summary reads."""

    def _write_weighted(self, tmp_path, rng, n_files=2, rows=120, d=30, k=6):
        for fi in range(n_files):
            recs = []
            for i in range(rows):
                ix = rng.choice(d, size=k, replace=False)
                vs = rng.normal(size=k)
                vs[0] = 0.0  # explicit zero entry: in-map, moment no-op
                recs.append({
                    "uid": f"{fi}-{i}",
                    "label": float(rng.uniform() > 0.5),
                    "features": [
                        {"name": f"x{j}", "term": "", "value": float(v)}
                        for j, v in zip(ix, vs)
                    ],
                    "offset": float(rng.normal()),
                    "weight": float(
                        rng.choice([0.0, 1.0, 2.0], p=[0.1, 0.6, 0.3])
                    ),
                })
            write_container(
                str(tmp_path / f"part-{fi}.avro"),
                schemas.TRAINING_EXAMPLE_AVRO, recs,
            )

    def _assert_matches(self, tmp_path, fmt):
        from photon_ml_tpu.data.stats import compute_summary
        from photon_ml_tpu.io.streaming import scan_stream_with_summary

        im1, st1 = scan_stream([str(tmp_path)], fmt)
        im2, st2, summary = scan_stream_with_summary([str(tmp_path)], fmt)
        assert st1 == st2
        assert dict(im1.items()) == dict(im2.items())
        loaded = AvroInputDataFormat().load([str(tmp_path)])
        ref = compute_summary(loaded.batch, loaded.num_features)
        for f in ("mean", "variance", "num_nonzeros", "max", "min",
                  "norm_l1", "norm_l2", "mean_abs"):
            np.testing.assert_allclose(
                np.asarray(getattr(summary, f)),
                np.asarray(getattr(ref, f)),
                rtol=1e-5, atol=1e-6, err_msg=f,
            )
        assert float(summary.count) == float(ref.count)

    def test_native_decode_path(self, tmp_path, rng):
        self._write_weighted(tmp_path, rng)
        self._assert_matches(tmp_path, AvroInputDataFormat())

    def test_python_codec_fallback(self, tmp_path, rng):
        self._write_weighted(tmp_path, rng)

        class NoNative(AvroInputDataFormat):
            def decode_file(self, path):
                return None

        self._assert_matches(tmp_path, NoNative())

    def test_prebuilt_map_drops_unknown_features(self, tmp_path, rng):
        from photon_ml_tpu.io.streaming import scan_stream_with_summary
        from photon_ml_tpu.utils.index_map import IndexMap

        self._write_weighted(tmp_path, rng, d=10, k=3)
        fmt = AvroInputDataFormat()
        full_map, _ = scan_stream([str(tmp_path)], fmt)
        # keep only half the vocabulary: dropped keys must contribute
        # nothing (same behavior as the remap in iter_rows)
        kept = {
            key: i
            for i, (key, _) in enumerate(sorted(full_map.items())[:5])
        }
        pruned = IndexMap(kept)
        _, _, summary = scan_stream_with_summary(
            [str(tmp_path)], fmt, index_map=pruned
        )
        assert np.asarray(summary.mean).shape[0] == len(kept)

    def test_glm_driver_uses_fused_scan(self, tmp_path, rng, monkeypatch):
        """Driver preprocess with normalization + no diagnostics reads
        the train dir ONCE (fused), not twice."""
        from photon_ml_tpu.cli.glm_driver import GLMDriver, GLMParams
        from photon_ml_tpu.ops.normalization import NormalizationType

        _write_files(tmp_path, rng)
        calls = {"scan": 0, "fused": 0, "summary": 0}
        import photon_ml_tpu.io.streaming as S

        real_fused = AvroInputDataFormat.stream_scan_with_summary
        real_summary = S.streaming_summary

        def counting_fused(self, paths, index_map=None):
            calls["fused"] += 1
            return real_fused(self, paths, index_map=index_map)

        def counting_summary(*a, **k):
            calls["summary"] += 1
            return real_summary(*a, **k)

        monkeypatch.setattr(
            AvroInputDataFormat, "stream_scan_with_summary", counting_fused
        )
        monkeypatch.setattr(S, "streaming_summary", counting_summary)
        p = GLMParams(
            train_dir=str(tmp_path),
            output_dir=str(tmp_path / "out"),
            streaming=True,
            normalization_type=NormalizationType.STANDARDIZATION,
            max_num_iterations=3,
            data_validation_type=__import__(
                "photon_ml_tpu.data.validators",
                fromlist=["DataValidationType"],
            ).DataValidationType.VALIDATE_DISABLED,
        )
        driver = GLMDriver(p)
        driver.preprocess()
        assert calls["fused"] == 1
        assert calls["summary"] == 0
        assert driver._summary is not None
        assert driver._norm is not None


class TestSpillCleanup:
    def test_atexit_sweep_removes_leaked_scratch(self, tmp_path):
        """A driver exception (traceback keeps the store alive, __del__
        never fires before exit) must not leak the spill directory: the
        atexit sweep removes every registered scratch dir."""
        script = r"""
import sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from photon_ml_tpu.io.streaming import _DiskChunkStore
from photon_ml_tpu.game.streaming import GameChunkStore

store = _DiskChunkStore(8, 4, sys.argv[1])
gstore = GameChunkStore(8, {"s": 4}, ["t"], sys.argv[1])
print("DIRS", store.dir, gstore.dir)
# keep both alive via an exception traceback (the leak scenario)
raise RuntimeError("driver blew up mid-stream")
"""
        out = subprocess.run(
            [sys.executable, "-c", script, str(tmp_path)],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert out.returncode != 0
        dirs = out.stdout.split("DIRS", 1)[1].split()
        assert len(dirs) == 2
        for d in dirs:
            assert not os.path.exists(d), d

    def test_close_unregisters(self, tmp_path):
        from photon_ml_tpu.io.streaming import (
            _DiskChunkStore,
            _LIVE_SPILL_DIRS,
        )

        store = _DiskChunkStore(8, 4, str(tmp_path))
        assert store.dir in _LIVE_SPILL_DIRS
        store.close()
        assert store.dir not in _LIVE_SPILL_DIRS
        assert not os.path.exists(store.dir)


class TestStreamBudget:
    def test_budget_rows(self):
        from photon_ml_tpu.io.streaming import (
            budgeted_rows,
            sparse_row_bytes,
            stream_budget_rows,
        )

        # no budget -> historical default
        assert stream_budget_rows(0, 100) == 65536
        assert stream_budget_rows(None, 100) == 65536
        # budget divides by row bytes, floored at min_rows
        assert stream_budget_rows(1000, 100) == 10
        assert stream_budget_rows(10, 100) == 8
        assert budgeted_rows(100, 1 << 30, sparse_row_bytes(16)) == 100
        assert budgeted_rows(100_000, 1024, sparse_row_bytes(1 << 20)) == 1


class TestStreamingFeatureSharded:
    """Streaming x feature-sharded composition: the guard is gone; the
    streamed sharded fit matches the replicated in-memory fit."""

    def test_matches_replicated_in_memory(self, tmp_path, rng):
        from photon_ml_tpu.optim.config import (
            OptimizerType,
            RegularizationType,
        )
        from photon_ml_tpu.parallel.mesh import (
            DATA_AXIS,
            MODEL_AXIS,
            make_mesh,
        )
        from photon_ml_tpu.training import train_streaming_feature_sharded

        _write_files(tmp_path, rng, n_files=3, rows_per_file=120, d=50, k=8)
        mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        fmt = AvroInputDataFormat()
        loaded = fmt.load([str(tmp_path)])
        for opt, lambdas in (
            (OptimizerType.LBFGS, [1.0, 0.1]),
            (OptimizerType.TRON, [1.0]),
        ):
            models_s, results_s, _ = train_streaming_feature_sharded(
                [str(tmp_path)], TaskType.LOGISTIC_REGRESSION, mesh=mesh,
                regularization_type=RegularizationType.L2,
                regularization_weights=lambdas, max_iter=40,
                rows_per_chunk=100, optimizer_type=opt,
            )
            models_m, _ = train_generalized_linear_model(
                loaded.batch, TaskType.LOGISTIC_REGRESSION,
                loaded.num_features,
                regularization_type=RegularizationType.L2,
                regularization_weights=lambdas, max_iter=40,
                optimizer_type=opt,
            )
            for lam in lambdas:
                np.testing.assert_allclose(
                    np.asarray(models_s[lam].coefficients.means),
                    np.asarray(models_m[lam].coefficients.means),
                    rtol=1e-3, atol=1e-3,
                )

    def test_elastic_net_and_overflow_cache(self, tmp_path, rng):
        """OWL-QN on the sharded streamed layout; a tiny sharded-cache
        budget forces the re-shard-per-pass overflow tier and must not
        change the result."""
        from photon_ml_tpu.optim.config import RegularizationType
        from photon_ml_tpu.parallel.mesh import (
            DATA_AXIS,
            MODEL_AXIS,
            make_mesh,
        )
        from photon_ml_tpu.training import train_streaming_feature_sharded

        _write_files(tmp_path, rng, n_files=3, rows_per_file=100, d=40, k=6)
        mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        kw = dict(
            regularization_type=RegularizationType.ELASTIC_NET,
            elastic_net_alpha=0.5,
            regularization_weights=[0.5],
            max_iter=30,
            rows_per_chunk=64,
        )
        models_a, _, _ = train_streaming_feature_sharded(
            [str(tmp_path)], TaskType.LOGISTIC_REGRESSION, mesh=mesh, **kw
        )
        models_b, _, _ = train_streaming_feature_sharded(
            [str(tmp_path)], TaskType.LOGISTIC_REGRESSION, mesh=mesh,
            sharded_cache_bytes=1, **kw
        )
        np.testing.assert_allclose(
            np.asarray(models_a[0.5].coefficients.means),
            np.asarray(models_b[0.5].coefficients.means),
            rtol=1e-6, atol=1e-7,
        )

    def test_driver_guard_removed_end_to_end(self, tmp_path, rng):
        """--streaming + --distributed feature passes validation and
        trains through the driver (the round-5 mutual-exclusion guard is
        gone); normalization on that path still rejects cleanly."""
        from photon_ml_tpu.cli.glm_driver import GLMDriver, GLMParams
        from photon_ml_tpu.ops.normalization import NormalizationType
        from photon_ml_tpu.optim.config import RegularizationType

        _write_files(tmp_path, rng, n_files=3, rows_per_file=100, d=40, k=6)
        p = GLMParams(
            train_dir=str(tmp_path),
            output_dir=str(tmp_path / "out"),
            streaming=True,
            distributed="feature",
            model_shards=2,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0],
            max_num_iterations=15,
        )
        driver = GLMDriver(p)
        driver.run()
        assert 1.0 in driver.models
        with pytest.raises(ValueError, match="normalization"):
            GLMParams(
                train_dir=str(tmp_path),
                output_dir=str(tmp_path / "out2"),
                streaming=True,
                distributed="feature",
                normalization_type=NormalizationType.STANDARDIZATION,
            ).validate()


class TestStreamedValidation:
    def test_driver_streams_validation_metrics(self, tmp_path, rng):
        """p.streaming validation consumes the validate dir through
        iter_chunks: AUC within 1e-3 of the exact in-memory value, loss
        exact; the in-memory loader is never called on the validate
        dir."""
        from photon_ml_tpu.cli.glm_driver import GLMDriver, GLMParams
        from photon_ml_tpu.optim.config import RegularizationType

        train = tmp_path / "train"
        val = tmp_path / "val"
        train.mkdir()
        val.mkdir()
        _write_files(train, rng, n_files=3, rows_per_file=120, d=40, k=6)
        _write_files(val, rng, n_files=2, rows_per_file=150, d=40, k=6)
        p = GLMParams(
            train_dir=str(train),
            output_dir=str(tmp_path / "out"),
            validate_dir=str(val),
            streaming=True,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0, 0.1],
            max_num_iterations=25,
        )
        driver = GLMDriver(p)
        # the streamed path must never materialize the validate dir
        real_load = AvroInputDataFormat.load

        def poisoned_load(self, paths, *a, **k):
            raise AssertionError(f"validate dir was materialized: {paths}")

        AvroInputDataFormat.load = poisoned_load
        try:
            driver.run()
        finally:
            AvroInputDataFormat.load = real_load
        assert driver.best_lambda in (1.0, 0.1)
        fmt = AvroInputDataFormat()
        vdata = fmt.load([str(val)], index_map=driver._data.index_map)
        for lam, model in driver.models.items():
            exact = driver._metrics_for(model, vdata.batch)
            streamed = driver.validation_metrics[lam]
            assert abs(exact["AUC"] - streamed["AUC"]) < 1e-3
            assert abs(
                exact["logistic_loss"] - streamed["logistic_loss"]
            ) < 1e-6

    def test_streaming_auc_histogram_accuracy(self, rng):
        """Histogram AUC vs the exact sort-based evaluator on weighted,
        tied, skewed score sets."""
        from photon_ml_tpu.evaluation.metrics import area_under_roc_curve
        from photon_ml_tpu.evaluation.streaming import StreamingAUC

        for seed in (0, 1, 2):
            r = np.random.default_rng(seed)
            n = 5000
            z = np.concatenate([
                r.normal(1.0, 2.0, n // 2), r.normal(-0.5, 0.5, n // 2)
            ])
            y = (r.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(float)
            w = r.choice([0.0, 0.5, 1.0, 2.0], size=n)
            z = np.round(z, 2)  # force ties
            exact = float(area_under_roc_curve(
                jnp.asarray(z, jnp.float32), jnp.asarray(y, jnp.float32),
                jnp.asarray(w, jnp.float32),
            ))
            acc = StreamingAUC()
            for lo in range(0, n, 700):  # chunked updates
                acc.update(z[lo:lo + 700], y[lo:lo + 700], w[lo:lo + 700])
            assert abs(acc.result() - exact) < 1e-3

    def test_streaming_rmse_and_loss_exact(self, rng):
        from photon_ml_tpu.evaluation.metrics import (
            mean_pointwise_loss,
            root_mean_squared_error,
        )
        from photon_ml_tpu.evaluation.streaming import (
            StreamingMeanLoss,
            StreamingRMSE,
        )
        from photon_ml_tpu.ops.losses import LOGISTIC

        n = 3000
        z = rng.normal(size=n).astype(np.float32)
        y = (rng.uniform(size=n) > 0.4).astype(np.float32)
        w = rng.uniform(size=n).astype(np.float32)
        exact_rmse = float(root_mean_squared_error(
            jnp.asarray(z), jnp.asarray(y), jnp.asarray(w)
        ))
        exact_loss = float(mean_pointwise_loss(
            LOGISTIC, jnp.asarray(z), jnp.asarray(y), jnp.asarray(w)
        ))
        r_acc = StreamingRMSE()
        l_acc = StreamingMeanLoss(LOGISTIC)
        for lo in range(0, n, 512):
            r_acc.update(z[lo:lo + 512], y[lo:lo + 512], w[lo:lo + 512])
            l_acc.update(z[lo:lo + 512], y[lo:lo + 512], w[lo:lo + 512])
        assert abs(r_acc.result() - exact_rmse) < 1e-6
        assert abs(l_acc.result() - exact_loss) < 1e-6
