"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests distribution with partitioned local-mode Spark
(photon-test SparkTestUtils.scala:27-70 — `local[4]`, never a real cluster);
we do the same with XLA host devices: 8 virtual CPU devices so every
shard_map / pjit path executes real collectives without TPU hardware.

Must run before any jax import in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon (real TPU tunnel); override before any backend is used.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration tests (subprocess boots)"
    )


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def game_example_schema():
    """Shared GameExample Avro schema for GAME file-path tests (single
    definition of the test data contract; see photon_ml_tpu.io.schemas
    for the production schemas)."""
    from photon_ml_tpu.io import schemas

    return {
        "name": "GameExample", "type": "record",
        "fields": [
            {"name": "uid", "type": ["null", "string"], "default": None},
            {"name": "response", "type": "double"},
            {
                "name": "metadataMap",
                "type": ["null", {"type": "map", "values": "string"}],
                "default": None,
            },
            {
                "name": "features",
                "type": {"type": "array", "items": schemas.FEATURE_AVRO},
            },
            {
                "name": "userFeatures",
                "type": {"type": "array", "items": "FeatureAvro"},
            },
        ],
    }
