"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests distribution with partitioned local-mode Spark
(photon-test SparkTestUtils.scala:27-70 — `local[4]`, never a real cluster);
we do the same with XLA host devices: 8 virtual CPU devices so every
shard_map / pjit path executes real collectives without TPU hardware.

Must run before any jax import in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Hermetic tier-1: a developer's persistent tile-schedule cache must not
# leak into (or be polluted by) the test run — tests opt in explicitly
# via schedule_cache.cache_scope(tmp_path).
os.environ.pop("PHOTON_TILE_CACHE_DIR", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon (real TPU tunnel); override before any backend is used.
import jax

jax.config.update("jax_platforms", "cpu")

# Installs the jax compat shim (jax.shard_map on releases where it still
# lives in jax.experimental) BEFORE test modules do `from jax import
# shard_map` at import time.
import photon_ml_tpu  # noqa: E402,F401

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration tests (subprocess boots)"
    )


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def game_example_schema():
    """Shared GameExample Avro schema for GAME file-path tests (single
    definition of the test data contract; see photon_ml_tpu.io.schemas
    for the production schemas)."""
    from photon_ml_tpu.io import schemas

    return {
        "name": "GameExample", "type": "record",
        "fields": [
            {"name": "uid", "type": ["null", "string"], "default": None},
            {"name": "response", "type": "double"},
            {
                "name": "metadataMap",
                "type": ["null", {"type": "map", "values": "string"}],
                "default": None,
            },
            {
                "name": "features",
                "type": {"type": "array", "items": schemas.FEATURE_AVRO},
            },
            {
                "name": "userFeatures",
                "type": {"type": "array", "items": "FeatureAvro"},
            },
        ],
    }
