"""--offheap-indexmap-dir: drivers consuming prebuilt native index stores
(reference: OptionNames.scala:47-48, PalDBIndexMapLoader,
cli/game/GAMEDriver.scala:89-97 prepareFeatureMaps)."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _native_or_skip():
    from photon_ml_tpu.utils import native_index

    try:
        native_index._lib()
    except Exception as e:  # pragma: no cover
        pytest.skip(f"native index store unavailable: {e}")


def test_load_offheap_index_map_shapes(tmp_path):
    _native_or_skip()
    from photon_ml_tpu.utils.native_index import (
        build_partitioned_index,
        load_offheap_index_map,
    )

    store = tmp_path / "index" / "global"
    pm = build_partitioned_index(
        (f"k{i}\t" for i in range(100)), str(store), num_partitions=3
    )
    pm.close()

    # direct store dir
    m1 = load_offheap_index_map(str(store))
    assert m1.size == 100
    m1.close()
    # parent dir with a single shard subdir
    m2 = load_offheap_index_map(str(tmp_path / "index"))
    assert m2.size == 100
    m2.close()
    # shard_name selection + partition-count validation
    m3 = load_offheap_index_map(
        str(tmp_path / "index"), shard_name="global", num_partitions=3
    )
    assert m3.get_index("k7\t") >= 0
    m3.close()
    with pytest.raises(ValueError):
        load_offheap_index_map(str(store), num_partitions=5)
    # per-shard mode must not silently fall back to a direct store
    with pytest.raises(OSError):
        load_offheap_index_map(str(store), shard_name="other")
    with pytest.raises(OSError):
        load_offheap_index_map(str(tmp_path / "index"), shard_name="missing")


def test_partition_routing_above_ten_partitions(tmp_path):
    """Lexicographic file ordering would misroute hash(key) % P for
    P >= 11 (partition '10' sorts before '2')."""
    _native_or_skip()
    from photon_ml_tpu.utils.native_index import (
        build_partitioned_index,
        load_offheap_index_map,
    )

    keys = [f"feat{i}\t" for i in range(500)]
    store = tmp_path / "global"
    pm = build_partitioned_index(iter(keys), str(store), num_partitions=12)
    pm.close()
    m = load_offheap_index_map(str(store), num_partitions=12)
    seen = {}
    for k in keys:
        i = m.get_index(k)
        assert i >= 0, f"{k} lost in partition routing"
        assert m.get_feature_name(i) == k
        seen[i] = k
    assert len(seen) == len(keys)
    m.close()


def test_pointer_roundtrip_through_index_map_load(tmp_path):
    """PartitionedIndexMap.save writes a pointer that IndexMap.load
    reopens — including after the output tree is relocated."""
    _native_or_skip()
    import shutil

    from photon_ml_tpu.utils.index_map import IndexMap
    from photon_ml_tpu.utils.native_index import build_partitioned_index

    out = tmp_path / "out"
    store = out / "index" / "global"
    pm = build_partitioned_index(
        (f"k{i}\t" for i in range(50)), str(store), num_partitions=2
    )
    pm.save(str(out / "feature-index" / "index.json"))

    reopened = IndexMap.load(str(out / "feature-index" / "index.json"))
    assert reopened.size == 50
    assert reopened.get_index("k3\t") == pm.get_index("k3\t")
    pm.close()
    reopened.close()

    # relocate the whole output tree: the relative pointer still resolves
    moved = tmp_path / "moved"
    shutil.move(str(out), str(moved))
    again = IndexMap.load(str(moved / "feature-index" / "index.json"))
    assert again.size == 50
    again.close()


def test_glm_driver_with_offheap_index(tmp_path, rng):
    _native_or_skip()
    from photon_ml_tpu.cli.feature_indexing_driver import run_feature_indexing
    from photon_ml_tpu.cli.glm_driver import GLMDriver, GLMParams
    from photon_ml_tpu.io.avro_codec import write_container
    from photon_ml_tpu.io import schemas

    train = tmp_path / "train"
    train.mkdir()
    w = rng.normal(size=6)
    recs = []
    for i in range(120):
        x = rng.normal(size=6)
        z = float(x @ w)
        recs.append({
            "uid": str(i),
            "label": float(1 / (1 + np.exp(-z)) > rng.uniform()),
            "features": [
                {"name": f"f{j}", "term": "", "value": float(x[j])}
                for j in range(6)
            ],
            "metadataMap": None,
            "weight": None,
            "offset": None,
        })
    write_container(
        str(train / "part.avro"), schemas.TRAINING_EXAMPLE_AVRO, recs
    )

    index_dir = tmp_path / "index"
    run_feature_indexing(
        [str(train)], str(index_dir), num_partitions=2, shard_name="global"
    )

    out = tmp_path / "out"
    params = GLMParams(
        train_dir=str(train),
        output_dir=str(out),
        regularization_weights=[1.0],
        offheap_indexmap_dir=str(index_dir),
        offheap_indexmap_num_partitions=2,
        distributed="off",
    )
    driver = GLMDriver(params)
    driver.run()
    assert driver.models
    # feature-index output is a pointer to the offheap store, not a dump
    meta = json.load(open(out / "feature-index" / "index.json"))
    assert meta["num_partitions"] == 2
    assert meta["size"] == 7  # 6 features + intercept
    # text models resolve feature names through the store
    text = (out / "models-text").glob("*")
    assert any(True for _ in text)


def test_game_driver_with_offheap_index(tmp_path, rng):
    _native_or_skip()
    from test_game_drivers import write_game_avro
    from photon_ml_tpu.cli.feature_indexing_driver import run_feature_indexing
    from photon_ml_tpu.cli.game_training_driver import (
        GameTrainingDriver,
        params_from_args,
    )

    train = tmp_path / "train"
    train.mkdir()
    write_game_avro(str(train / "p.avro"), rng, n=160)

    index_dir = tmp_path / "index"
    run_feature_indexing(
        [str(train)], str(index_dir), feature_bags=["features"],
        num_partitions=2, shard_name="g",
    )
    run_feature_indexing(
        [str(train)], str(index_dir), feature_bags=["userFeatures"],
        num_partitions=2, shard_name="u",
    )

    params = params_from_args([
        "--train-input-dirs", str(train),
        "--output-dir", str(tmp_path / "out"),
        "--feature-shard-id-to-feature-section-keys-map",
        "g:features|u:userFeatures",
        "--fixed-effect-data-configurations", "global:g",
        "--fixed-effect-optimization-configurations",
        "global:10,1e-6,0.1,1,LBFGS,L2",
        "--random-effect-data-configurations",
        "per-user:userId,u,1,none,none,none,index_map",
        "--random-effect-optimization-configurations",
        "per-user:10,1e-6,1.0,1,LBFGS,L2",
        "--updating-sequence", "global,per-user",
        "--num-iterations", "2",
        "--offheap-indexmap-dir", str(index_dir),
        "--offheap-indexmap-num-partitions", "2",
        "--distributed", "off",
    ])
    driver = GameTrainingDriver(params)
    driver.run()
    assert driver.results
    objective = driver.results[0][1].objective_history
    assert objective[-1] <= objective[0]
