"""Twin-run determinism harness tests (ISSUE 19 satellite 4).

Three layers:

* unit — ``stable_seed``, ``byte_diff_trees``, ``run_target`` dispatch,
  ``_child_env`` hygiene;
* positive control — the harness MUST catch the intentionally
  hash-order-dependent writer (``control_hash_order``). A twin run that
  reports it byte-identical means the harness itself is broken;
* regression — the real PL016 defect this round fixed (hash()-seeded
  retry jitter) stays fixed ACROSS interpreters: two children under
  different ``PYTHONHASHSEED`` values must draw the same backoff.

The full six-class gate matrix lives in ``dev-scripts/determinism.sh``;
one representative gate class (the wire-frame family) is twin-run here
so the tier-1 suite exercises the subprocess plumbing end to end.
"""

import os
import subprocess
import sys

import pytest

from photon_ml_tpu.testing import determinism as det
from photon_ml_tpu.testing import determinism_targets as dt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestUnits:
    def test_stable_seed_is_process_stable_and_distinct(self):
        # crc32 of the joined text: same parts -> same seed, every
        # process, every PYTHONHASHSEED
        import zlib

        s = det.stable_seed("seam", 3)
        assert s == zlib.crc32(b"seam:3")
        assert det.stable_seed("seam", 3) == s
        assert det.stable_seed("seam", 4) != s

    def test_byte_diff_trees_identical(self, tmp_path):
        for run in ("a", "b"):
            d = tmp_path / run / "sub"
            d.mkdir(parents=True)
            (d / "x.json").write_bytes(b'{"k": 1}')
            (tmp_path / run / "y.bin").write_bytes(b"\x00\x01")
        assert det.byte_diff_trees(
            str(tmp_path / "a"), str(tmp_path / "b")
        ) is None

    def test_byte_diff_trees_names_file_and_offset(self, tmp_path):
        for run, tail in (("a", b"AB"), ("b", b"AC")):
            d = tmp_path / run
            d.mkdir()
            (d / "same.bin").write_bytes(b"equal")
            (d / "diff.bin").write_bytes(b"xx" + tail)
        msg = det.byte_diff_trees(str(tmp_path / "a"), str(tmp_path / "b"))
        assert msg == (
            "diff.bin: first byte divergence at offset 3 (4 vs 4 bytes)"
        ), msg

    def test_byte_diff_trees_missing_file(self, tmp_path):
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        (tmp_path / "a" / "only.txt").write_bytes(b"x")
        msg = det.byte_diff_trees(str(tmp_path / "a"), str(tmp_path / "b"))
        assert msg == "only.txt: present only in the first run", msg
        msg = det.byte_diff_trees(str(tmp_path / "b"), str(tmp_path / "a"))
        assert msg == "only.txt: present only in the second run", msg

    def test_run_target_unknown_name(self, tmp_path):
        with pytest.raises(KeyError, match="unknown determinism target"):
            det.run_target("no_such_artifact", str(tmp_path))

    def test_child_env_hygiene(self):
        # builds the child environment without mutating the parent's
        before = dict(os.environ)
        env = det._child_env("4242", "Pacific/Kiritimati")
        assert dict(os.environ) == before
        assert env["PYTHONHASHSEED"] == "4242"
        assert env["TZ"] == "Pacific/Kiritimati"
        assert det._REPO_ROOT in env["PYTHONPATH"].split(os.pathsep)

    def test_gate_matrix_excludes_the_control(self):
        # the positive control must never ride in the gate set: it is
        # built to diverge, and the gate exits nonzero on divergence
        assert "control_hash_order" not in dt.TARGETS
        assert "control_hash_order" in dt.ALL_TARGETS
        assert set(dt.ALL_TARGETS) == set(dt.TARGETS) | set(
            dt.CONTROL_TARGETS
        )

    def test_twin_run_surfaces_child_failure(self, tmp_path):
        # a crashing child is a TwinRunError (harness defect), never a
        # quiet "identical" verdict over two empty trees
        with pytest.raises(det.TwinRunError, match="no_such_artifact"):
            det.twin_run("no_such_artifact", base_dir=str(tmp_path))


class TestPositiveControl:
    def test_harness_catches_hash_order_dependent_writer(self, tmp_path):
        res = det.twin_run("control_hash_order", base_dir=str(tmp_path))
        assert res.identical is False
        assert res.divergence is not None
        assert res.divergence.startswith("control.txt:"), res.divergence
        # and the result serializes for the gate report
        d = res.to_dict()
        assert d["target"] == "control_hash_order"
        assert d["identical"] is False


class TestGateClasses:
    @pytest.mark.slow
    def test_wire_frames_twin_run_is_byte_identical(self, tmp_path):
        res = det.twin_run("wire_frames", base_dir=str(tmp_path))
        assert res.identical, res.divergence
        frames = os.path.join(
            str(tmp_path), "wire_frames.run0", "frames.bin"
        )
        assert os.path.getsize(frames) > 0

    @pytest.mark.slow
    def test_full_matrix_is_byte_identical(self, tmp_path):
        report = det.run_matrix(
            str(tmp_path),
            report_path=str(tmp_path / "gate.json"),
        )
        assert report["ok"] is True, report
        assert sorted(report["classes"]) == sorted(dt.TARGETS)
        assert os.path.exists(tmp_path / "gate.json")


class TestSeedRegressions:
    def _child_eval(self, code: str, seed: str) -> str:
        env = det._child_env(seed, "UTC")
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=env,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        return proc.stdout.strip()

    def test_fixed_seeds_identical_across_hash_seeds(self):
        """The real defects PL016 caught, re-run across interpreters:
        the retry plane's backoff jitter (was hash((seam, attempt))-
        seeded — differed per process) and bench's flood-payload PRNG
        (was hash(key)-seeded — parent vs relaunched child built
        different payloads, drifting cache-hit accounting). Both crc32
        fixes must draw identically in two children with different
        PYTHONHASHSEEDs. One child pair covers both fixes."""
        code = (
            "import zlib, numpy as np\n"
            "from photon_ml_tpu.reliability.retry import "
            "RetryPolicy, _backoff_s\n"
            "p = RetryPolicy()\n"
            "print([round(_backoff_s(p, 'chunk_read', a), 12) "
            "for a in (1, 2, 3)])\n"
            "key = ('warm', 3, 128)\n"
            "seed = zlib.crc32("
            "f'{key[0]}:{key[1]}:{key[2]}'.encode('utf-8'))\n"
            "prng = np.random.default_rng(seed & 0x7FFFFFFF)\n"
            "print(prng.integers(0, 2**31, size=8).tolist())"
        )
        a = self._child_eval(code, "0")
        b = self._child_eval(code, "4242")
        assert a == b, (a, b)
