"""Unified (data × feature × entity × grid) mesh (parallel/unified_mesh.py
+ game/unified.py): a λ-grid sweep over an entity-sharded GAME model as
ONE shard_mapped program.

Parity matrix pinned here (ISSUE 20):

- unified grid CD == per-λ pod CD on the SAME entity shard count
  (objectives ~1e-6 relative; banks inside the pod fp32 envelopes);
- unified grid CD == per-λ replicated CD at N ∈ {1, 2, 4, 8} entity
  shards — the entity axis is a layout choice, not a math change;
- FixedEffectCoordinate.update_model_grid on the (data, model) mesh ==
  the cold sequential feature-sharded sweep, with and without
  down-sampling (λ-independent draw, one shared weight rewrite);
- duplicate-λ members stay BITWISE identical — the batched while_loop
  freeze mask never lets a converged member's rows drift;
- contracts: ONE batched readback per CD iteration, ZERO relowerings on
  a warmed same-shape run, and the SHARDING.md entry-point inventory is
  strictly below the pre-unification count (38) — the unified program
  REPLACED per-combination entry points instead of adding more.

The streaming × sharded leg is covered transitively rather than by a
direct pairing: test_streaming_game.TestStreamingGameParity pins
streamed CD == in-memory CD, test_pod_game pins sharded CD ==
replicated CD and streamed × sharded == streamed × replicated through
the training driver, and this file pins unified == pod CD — the chain
closes without a bespoke streaming oracle.
"""

import os
import re
import sys

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from photon_ml_tpu.game.coordinate import (
    FixedEffectCoordinate,
    PodRandomEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import CoordinateDescent
from photon_ml_tpu.game.pod import EntityShardSpec
from photon_ml_tpu.game.unified import GridShardedREBank, run_game_grid
from photon_ml_tpu.optim.config import OptimizerConfig, OptimizerType
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    ENTITY_AXIS,
    GRID_AXIS,
    MODEL_AXIS,
    entity_mesh,
    make_mesh,
)
from photon_ml_tpu.parallel.unified_mesh import resolve_mesh
from photon_ml_tpu.reliability.checkpoint import GridCheckpointer
from photon_ml_tpu.task import TaskType
from photon_ml_tpu import training

sys.path.insert(0, os.path.dirname(__file__))

from test_pod_game import _problem, _synthetic_re  # noqa: E402

LAMBDAS = [0.1, 0.5, 1.0, 2.0]
TASK = TaskType.LOGISTIC_REGRESSION


@pytest.fixture(scope="module")
def game_data():
    """Shared small GAME dataset + FE problem + per-λ replicated oracle
    cache (the replicated CD baseline is λ-keyed and reused across the
    entity-shard parametrization)."""
    ds, red = _synthetic_re(n=96, E=11)
    fe_problem = create_glm_problem(
        TASK, ds.shards["s"].dim, config=OptimizerConfig(max_iter=5)
    )
    cache = {}

    def replicated_ref(lam):
        if lam not in cache:
            coords = {
                "fixed": FixedEffectCoordinate(
                    name="fixed", dataset=ds, problem=fe_problem,
                    feature_shard_id="s", reg_weight=0.1,
                ),
                "per-user": RandomEffectCoordinate(
                    name="per-user", dataset=ds, re_dataset=red,
                    problem=_problem(reg_weight=lam),
                ),
            }
            cache[lam] = CoordinateDescent(coords, ds, TASK).run(2)
        return cache[lam]

    return ds, red, fe_problem, replicated_ref


def _run_unified(game_data, n_ent, lambdas=LAMBDAS, num_iterations=2,
                 **kw):
    ds, red, fe_problem, _ = game_data
    plan = resolve_mesh(grid_size=len(lambdas), entity_shards=n_ent)
    res = run_game_grid(
        plan, ds, red, fe_problem, _problem(), lambdas,
        feature_shard_id="s", fe_reg_weight=0.1,
        num_iterations=num_iterations, **kw,
    )
    return plan, res


# ---------------------------------------------------------------------------
# mesh-shape policy
# ---------------------------------------------------------------------------


class TestResolveMesh:
    def test_prefers_divisor_rows(self):
        # 8 devices, N=2 -> 4 usable rows; G=6 -> 3 divides, 4 doesn't.
        plan = resolve_mesh(grid_size=6, entity_shards=2)
        assert plan.grid_rows == 3
        assert plan.members_per_row == 2
        assert plan.grid_padded == 6  # no padding members
        assert tuple(plan.mesh.axis_names) == (GRID_AXIS, ENTITY_AXIS)
        assert plan.mesh.devices.shape == (3, 2)

    def test_prime_grid_falls_to_one_row(self):
        # N=4 -> 2 usable rows; G=7 is prime above 2, and 1 always
        # divides, so the policy takes 1 row x 7 members over padding.
        plan = resolve_mesh(grid_size=7, entity_shards=4)
        assert (plan.grid_rows, plan.members_per_row) == (1, 7)
        assert plan.grid_padded == 7
        padded = plan.pad_members(LAMBDAS)
        assert len(padded) == 7 and padded[4:] == [LAMBDAS[-1]] * 3

    def test_entity_shards_minus_one_takes_all_devices(self):
        plan = resolve_mesh(grid_size=4, entity_shards=-1)
        assert plan.entity_shards == len(jax.devices())
        assert plan.grid_rows == 1

    def test_per_device_accounting(self):
        per_member = 1000
        plan = resolve_mesh(
            grid_size=8, entity_shards=2, member_bank_bytes=per_member,
            budget=10_000,
        )
        # 4 rows x 2 members/row, each device holds 2 members / 2 shards
        assert plan.per_device_bank_bytes == (
            plan.members_per_row * per_member // plan.entity_shards
        )
        assert plan.fits_budget
        tight = resolve_mesh(
            grid_size=8, entity_shards=2, member_bank_bytes=per_member,
            budget=plan.per_device_bank_bytes - 1,
        )
        assert not tight.fits_budget

    def test_sharding_spec(self):
        plan = resolve_mesh(grid_size=4, entity_shards=2)
        assert plan.grid_entity_sharding().spec == P(GRID_AXIS, ENTITY_AXIS)

    def test_bad_args(self):
        with pytest.raises(ValueError):
            resolve_mesh(grid_size=0)
        with pytest.raises(ValueError):
            resolve_mesh(grid_size=2, entity_shards=99)
        with pytest.raises(ValueError):
            resolve_mesh(grid_size=2, feature_blocks=0)
        with pytest.raises(ValueError):
            resolve_mesh(grid_size=2).pad_members([])

    def test_grid_bank_bytes_entity_sharded(self):
        total = training.grid_bank_bytes(4, 64)
        for n in (2, 4, 8):
            per_dev = training.grid_bank_bytes(4, 64, entity_shards=n)
            assert per_dev == -(-total // n)  # ceil(total / N)

    def test_resolve_grid_mode_uses_per_device_figure(self):
        # A grid too big for the replicated budget fits once the bank
        # rows split over 8 entity shards.
        kw = dict(
            num_weights=16, dim=4096,
            optimizer_type=OptimizerType.LBFGS,
        )
        budget = training.grid_bank_bytes(16, 4096) // 4
        assert training.resolve_grid_mode(
            "auto", memory_budget_bytes=budget, **kw
        ) == "sequential"
        assert training.resolve_grid_mode(
            "auto", memory_budget_bytes=budget, entity_shards=8, **kw
        ) == "batched"


# ---------------------------------------------------------------------------
# grid-sharded bank
# ---------------------------------------------------------------------------


class TestGridBank:
    def test_zeros_layout_and_per_device_bytes(self):
        plan = resolve_mesh(grid_size=4, entity_shards=2)
        spec = EntityShardSpec(2, 11)
        bank = GridShardedREBank.zeros(
            plan.mesh, spec, 4, plan.grid_padded, 12
        )
        assert bank.data.shape == (plan.grid_padded, spec.bank_rows, 12)
        assert bank.data.sharding.spec == P(GRID_AXIS, ENTITY_AXIS)
        total = bank.data.size * 4
        per_dev = bank.per_device_bytes()
        assert per_dev <= total // (plan.grid_rows * plan.entity_shards)

    def test_member_globals_round_trip(self):
        plan = resolve_mesh(grid_size=3, entity_shards=2)
        spec = EntityShardSpec(2, 7)
        rng = np.random.default_rng(0)
        members = [
            rng.normal(size=(7, 5)).astype(np.float32) for _ in range(3)
        ]
        bank = GridShardedREBank.from_member_globals(
            plan.mesh, spec, 3, plan.pad_members(members)
        )
        for g in range(3):
            np.testing.assert_array_equal(
                np.asarray(bank.member_global(g)), members[g]
            )
        # padding member duplicates the last λ's rows
        assert bank.grid_padded >= 3

    def _trained_like_bank(self):
        """A non-trivial grid bank without a training run (the
        checkpoint plane only cares about bytes and placement)."""
        plan = resolve_mesh(grid_size=3, entity_shards=2)
        spec = EntityShardSpec(2, 11)
        rng = np.random.default_rng(7)
        members = [
            rng.normal(size=(11, 4)).astype(np.float32) for _ in range(3)
        ]
        return GridShardedREBank.from_member_globals(
            plan.mesh, spec, 3, plan.pad_members(members)
        )

    def test_snapshot_restore_is_bitwise_and_resharded(self, tmp_path):
        bank = self._trained_like_bank()
        ck = GridCheckpointer(str(tmp_path), {"cfg": 1})
        ck.save_grid_bank("re", bank.snapshot(), bank.layout())
        assert ck.has_grid_bank("re")
        loaded, layout = ck.load_grid_bank(
            "re", expect_layout=bank.layout()
        )
        assert layout == {k: int(v) for k, v in bank.layout().items()}
        restored = GridShardedREBank.restore(
            bank.mesh, bank.spec, bank.grid_size, loaded
        )
        np.testing.assert_array_equal(
            np.asarray(restored.data), np.asarray(bank.data)
        )
        # restore re-shards DEVICE-side back onto P(grid, entity) —
        # never a host [E, d] gather (PL012 guards the export scopes).
        assert restored.data.sharding.spec == P(GRID_AXIS, ENTITY_AXIS)

    def test_restore_guards_layout_and_shape(self, tmp_path):
        bank = self._trained_like_bank()
        ck = GridCheckpointer(str(tmp_path), {"cfg": 1})
        ck.save_grid_bank("re", bank.snapshot(), bank.layout())
        bad = dict(bank.layout())
        bad["num_shards"] = 99
        with pytest.raises(ValueError, match="num_shards"):
            ck.load_grid_bank("re", expect_layout=bad)
        with pytest.raises(ValueError, match="does not match"):
            GridShardedREBank.restore(
                bank.mesh, bank.spec, bank.grid_size,
                bank.snapshot()[:, :-1, :],
            )

    def test_missing_snapshot_is_none(self, tmp_path):
        ck = GridCheckpointer(str(tmp_path), {"cfg": 1})
        assert not ck.has_grid_bank("nope")
        assert ck.load_grid_bank("nope") is None


# ---------------------------------------------------------------------------
# parity matrix
# ---------------------------------------------------------------------------


class TestUnifiedParity:
    @pytest.mark.parametrize("n_ent", [1, 2, 4, 8])
    def test_matches_replicated_cd(self, game_data, n_ent):
        """One unified program at N entity shards == the per-λ
        replicated CD oracle. The entity axis is a layout choice."""
        _, _, _, replicated_ref = game_data
        _, res = _run_unified(game_data, n_ent)
        for gi, lam in enumerate(LAMBDAS):
            ref = replicated_ref(lam)
            got = [h[gi] for h in res.objective_history]
            np.testing.assert_allclose(
                got, ref.objective_history, rtol=1e-4,
                err_msg=f"lambda={lam} n_ent={n_ent}",
            )
            np.testing.assert_allclose(
                np.asarray(res.re_bank.member_global(gi)),
                np.asarray(ref.model.models["per-user"].bank),
                atol=2e-3, rtol=2e-3, err_msg=f"lambda={lam}",
            )
            np.testing.assert_allclose(
                np.asarray(res.fe_means(gi)),
                np.asarray(ref.model.models["fixed"].model.means),
                atol=2e-3, rtol=2e-3, err_msg=f"lambda={lam}",
            )

    def test_matches_pod_cd(self, game_data):
        """Tightest pairing: the unified grid against per-λ pod CD on
        the SAME entity mesh — identical routing, hash placement and
        reduction order, so objectives agree to ~1e-6 relative."""
        ds, red, fe_problem, _ = game_data
        _, res = _run_unified(game_data, n_ent=2)
        for gi, lam in enumerate(LAMBDAS):
            coords = {
                "fixed": FixedEffectCoordinate(
                    name="fixed", dataset=ds, problem=fe_problem,
                    feature_shard_id="s", reg_weight=0.1,
                ),
                "per-user": PodRandomEffectCoordinate(
                    name="per-user", dataset=ds, re_dataset=red,
                    problem=_problem(reg_weight=lam),
                    mesh=entity_mesh(2),
                ),
            }
            ref = CoordinateDescent(coords, ds, TASK).run(2)
            got = [h[gi] for h in res.objective_history]
            np.testing.assert_allclose(
                got, ref.objective_history, rtol=2e-4,
                err_msg=f"lambda={lam}",
            )
            np.testing.assert_allclose(
                np.asarray(res.re_bank.member_global(gi)),
                np.asarray(ref.model.models["per-user"].bank),
                atol=2e-3, rtol=2e-3, err_msg=f"lambda={lam}",
            )

    def test_duplicate_lambda_members_bitwise_identical(self, game_data):
        """Freeze-mask bit-stability: two members with the SAME λ run
        the same masked while_loop iterates, so their banks and
        objective columns are BITWISE equal — a converged member's rows
        cannot drift under other members' continued iterations."""
        _, res = _run_unified(game_data, n_ent=2,
                              lambdas=[0.5, 0.5, 2.0, 0.5])
        for h in res.objective_history:
            assert float(h[0]) == float(h[1]) == float(h[3])
        b0 = np.asarray(res.re_bank.member_global(0))
        np.testing.assert_array_equal(
            b0, np.asarray(res.re_bank.member_global(1))
        )
        np.testing.assert_array_equal(
            b0, np.asarray(res.re_bank.member_global(3))
        )
        np.testing.assert_array_equal(
            np.asarray(res.fe_means(0)), np.asarray(res.fe_means(1))
        )


# ---------------------------------------------------------------------------
# feature-sharded FE grid inside the GAME coordinate
# ---------------------------------------------------------------------------


class TestFeatureShardedGridCoordinate:
    def _coord(self, ds, fe_problem, mesh=None, **kw):
        return FixedEffectCoordinate(
            name="fixed", dataset=ds, problem=fe_problem,
            feature_shard_id="s", mesh=mesh, **kw,
        )

    def test_grid_matches_cold_sequential(self, game_data):
        """update_model_grid on the (data, model) mesh == one cold
        feature-sharded solve per λ."""
        ds, _, fe_problem, _ = game_data
        mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        grid = self._coord(ds, fe_problem, mesh).update_model_grid(LAMBDAS)
        assert len(grid) == len(LAMBDAS)
        for lam, (model, result) in zip(LAMBDAS, grid):
            seq_model, seq_result = self._coord(
                ds, fe_problem, mesh, reg_weight=lam
            ).update_model(None)
            assert float(result.value) == pytest.approx(
                float(seq_result.value), rel=1e-5
            ), lam
            np.testing.assert_allclose(
                np.asarray(model.model.means),
                np.asarray(seq_model.model.means),
                atol=1e-3, err_msg=f"lambda={lam}",
            )

    def test_down_sampled_grid_matches_sequential_sampled(self, game_data):
        """Down-sampling composes with the grid solve: the draw is
        λ-independent (same PRNG stream as the sequential path), so the
        whole grid solves against the same sampled batch."""
        ds, _, fe_problem, _ = game_data
        kw = dict(down_sampling_rate=0.7, sampler_seed=3)
        grid = self._coord(ds, fe_problem, **kw).update_model_grid(LAMBDAS)
        for lam, (model, result) in zip(LAMBDAS, grid):
            seq_model, seq_result = self._coord(
                ds, fe_problem, reg_weight=lam, **kw
            ).update_model(None)
            assert float(result.value) == pytest.approx(
                float(seq_result.value), rel=1e-5
            ), lam
            np.testing.assert_allclose(
                np.asarray(model.model.means),
                np.asarray(seq_model.model.means),
                atol=1e-3, err_msg=f"lambda={lam}",
            )


# ---------------------------------------------------------------------------
# program contracts
# ---------------------------------------------------------------------------


class TestUnifiedContracts:
    def test_one_batched_readback_per_iteration(self, game_data):
        """The whole G-member sweep costs ONE device->host readback per
        CD iteration — the per-iteration objective vector (and deferred
        tracker stats) travel in a single overlap.fetch_all."""
        with overlap.overlap_scope(True):
            overlap.reset_readback_stats()
            _run_unified(game_data, n_ent=2, num_iterations=3)
            assert overlap.readback_stats() == 3

    def test_zero_relowerings_when_warm(self, game_data):
        """A warmed same-shape run lowers NOTHING: every program in the
        unified sweep (route/update/score/objective) is cached at
        module scope, so iteration count and λ values are data."""
        import jax._src.test_util as jtu

        _run_unified(game_data, n_ent=2, num_iterations=1)  # warm
        with jtu.count_jit_and_pmap_lowerings() as count:
            _run_unified(game_data, n_ent=2,
                         lambdas=[0.2, 0.7, 1.5, 3.0], num_iterations=2)
        assert count[0] == 0, count[0]

    def test_sharding_inventory_shrank(self):
        """SUBTRACTIVE success metric: the unified program REPLACED
        per-combination entry points (five distributed fit builders
        collapsed to wrappers, fit/hdiag variants merged), so the PL011
        SPMD entry-point inventory lands strictly below the
        pre-unification count of 38."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, "SHARDING.md")) as f:
            text = f.read()
        m = re.search(r"(\d+) entry point\(s\)\.", text)
        assert m, "SHARDING.md inventory line missing"
        assert int(m.group(1)) < 38, m.group(0)
        assert "photon_ml_tpu/game/unified.py" in text
