"""Planet-scale serving tests (ISSUE 12): the scatter/gather routing
tier over entity-sharded shard-server fleets.

The acceptance bar: routed scores are BITWISE equal to the
single-server serving path and the batch scorer at N in {1, 2, 4}
shards — including across a router-coordinated two-step generation
flip — while a dead/stalled shard degrades its OWN entities to the
FE-only score (bitwise) instead of failing anything, and the
generation-keyed hot-entity cache serves zipf head traffic bitwise and
never across generations. The interleaving schedule families drive the
router fan-out/cache/swap plane deterministically: every call terminal,
zero deadlocks, no cross-generation score ever emitted.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from photon_ml_tpu import ownership
from photon_ml_tpu.serving import (
    MicroBatcher,
    NoShardAvailable,
    RoutingPolicy,
    ServingModel,
    ServingPrograms,
    ShardRouter,
    ShardServer,
    bank_from_arrays,
    request_from_record,
    requests_from_dataset,
)
from photon_ml_tpu.serving.routing import (
    FE_SLOT,
    HotEntityCache,
    TransportError,
)
from photon_ml_tpu.game.data import build_game_dataset
from photon_ml_tpu.game.model_io import LoadedGameModel
from tests.test_serving import (
    SHARDS,
    _wait_until,
    batch_reference_scores,
    make_bank,
    synth_model,
    synth_records,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LADDER = (1, 8)


def user_ids(lm):
    """The model's sorted entity universe — the router's index input."""
    return sorted(lm.random_effects["per-user"][2])


def build_fleet(lm, ds, n_shards, *, stager_for=None):
    """N in-process shard-servers over real sockets, each loading ONE
    entity shard of the model through the artifact-path bank builder."""
    servers = []
    for s in range(n_shards):
        bank = make_bank(lm, ds, entity_shard=(s, n_shards))
        sm = ServingModel(
            bank,
            ServingPrograms(LADDER),
            partial=True,
            entity_shard=(s, n_shards),
        )
        servers.append(
            ShardServer(
                sm,
                SHARDS,
                (s, n_shards),
                stager=stager_for(s, sm) if stager_for else None,
            ).start()
        )
    return servers


def build_router(servers, lm, **kw):
    kw.setdefault("shard_configs", SHARDS)
    router = ShardRouter(
        [("127.0.0.1", srv.port) for srv in servers],
        entity_ids={"userId": user_ids(lm)},
        **kw,
    )
    router.connect()
    return router


def close_fleet(servers, router=None):
    if router is not None:
        router.close()
    for srv in servers:
        srv.close()


def single_server_scores(lm, ds):
    bank = make_bank(lm, ds)
    programs = ServingPrograms(LADDER)
    programs.ensure_compiled(bank)
    with MicroBatcher(lambda: bank, programs) as mb:
        return np.asarray(
            [mb.score(r) for r in requests_from_dataset(ds, bank)],
            np.float32,
        )


class TestRoutedParity:
    @pytest.mark.parametrize("n_shards", [1, 2, 4])
    def test_routed_bitwise_vs_single_server_and_batch(
        self, rng, n_shards
    ):
        """The acceptance bar at N in {1, 2, 4}: every routed margin is
        bit-for-bit the batch scorer's AND the single-server request
        path's, including offsets and the unknown-entity row."""
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        ref_batch = batch_reference_scores(lm, ds)
        ref_single = single_server_scores(lm, ds)
        assert np.array_equal(ref_single, ref_batch)
        servers = build_fleet(lm, ds, n_shards)
        router = build_router(servers, lm)
        try:
            got = [router.score_record(r) for r in recs]
            assert np.array_equal(
                np.asarray(got, np.float32), ref_batch
            ), "routed scores must be bitwise the batch scorer's"
            # unknown entity (synth_model drops user6): routed is NOT
            # degraded — same semantics as the single-server path
            for rec, out in zip(recs, got):
                if rec["metadataMap"]["userId"] == "user6":
                    assert out.degraded is False
            assert all(out.generation == 1 for out in got)
            # fan-out never exceeds the owners + FE provider (one RE
            # type here: exactly one shard per request)
            assert all(out.fanout == 1 for out in got)
        finally:
            close_fleet(servers, router)

    def test_partial_recomposition_matches_full_program(self, rng):
        """Device-level decomposition contract: fe + spec-ordered f32
        term adds + offset == the full-margin program, bitwise."""
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        bank = make_bank(lm, ds)
        programs = ServingPrograms(LADDER)
        reqs = requests_from_dataset(ds, bank)
        with MicroBatcher(lambda: bank, programs) as mb:
            full = [mb.score(r) for r in reqs]
        with MicroBatcher(
            lambda: bank, programs, partial=True
        ) as mb:
            parts = [mb.score(r) for r in reqs]
        from photon_ml_tpu.serving.programs import term_entries

        names = [e[1] for e in term_entries(bank.spec)]
        for req, f, p in zip(reqs, full, parts):
            total = np.float32(p.fe)
            for name in names:
                total = np.float32(total + np.float32(p.terms[name]))
            total = np.float32(total + np.float32(req.offset))
            assert np.float32(f) == total

    def test_topology_op_and_status_publish_shard_layout(self, rng):
        """Satellite: operators and the router discover the fleet
        layout from the wire — shard index/count, the ownership rule,
        spec term entries — via the topology op AND the status block."""
        from tests.test_serving_frontend import Client

        recs = synth_records(rng, n=10)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        servers = build_fleet(lm, ds, 2)
        try:
            c = Client(servers[1].port)
            topo = c.ask({"op": "topology", "uid": "t1"})
            assert topo["uid"] == "t1" and topo["status"] == "ok"
            assert topo["shard_index"] == 1
            assert topo["shard_count"] == 2
            assert topo["rule"] == ownership.OWNERSHIP_RULE
            assert topo["generation"] == 1
            assert topo["partial"] is True and topo["ready"] is True
            assert topo["entries"] == [
                ["re", "per-user", ["userId"], "u"]
            ]
            status = c.ask({"op": "status"})
            assert status["shard"]["shard_index"] == 1
            assert status["shard"]["rule"] == ownership.OWNERSHIP_RULE
            c.close()
        finally:
            close_fleet(servers)

    def test_misordered_fleet_is_refused(self, rng):
        """A fleet whose addresses disagree with the shards' own
        indexes would serve every coefficient from the wrong host —
        connect() refuses it outright."""
        recs = synth_records(rng, n=10)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        servers = build_fleet(lm, ds, 2)
        try:
            router = ShardRouter(
                [
                    ("127.0.0.1", servers[1].port),
                    ("127.0.0.1", servers[0].port),
                ],
                entity_ids={"userId": user_ids(lm)},
                shard_configs=SHARDS,
            )
            with pytest.raises(ValueError, match="ownership rule|index"):
                router.connect()
            router.close()
        finally:
            close_fleet(servers)

    def test_router_requires_sorted_entity_universe(self):
        with pytest.raises(ValueError, match="SORTED"):
            ShardRouter(
                [("127.0.0.1", 1)],
                entity_ids={"userId": ["b", "a"]},
            )


class TestDegradation:
    def test_dead_shard_degrades_its_entities_fe_only(self, rng):
        """One SHARD dies, not the service: its entities answer the
        FE-only score (bitwise the batch scorer's FE-only path) with
        degraded=True; the other shard's entities stay exact and
        non-degraded. Nothing raises."""
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        fe_only = LoadedGameModel()
        fe_only.fixed_effects = dict(lm.fixed_effects)
        ref_full = batch_reference_scores(lm, ds)
        ref_fe = batch_reference_scores(fe_only, ds)
        servers = build_fleet(lm, ds, 2)
        router = build_router(
            servers,
            lm,
            policy=RoutingPolicy(subrequest_timeout_s=1.0),
        )
        try:
            servers[1].close()  # SIGKILL-equivalent for its sockets
            ids = user_ids(lm)
            for i, rec in enumerate(recs[:20]):
                uid = rec["metadataMap"]["userId"]
                out = router.score_record(rec)
                code = (
                    ids.index(uid) if uid in ids else -1
                )
                owner = (
                    ownership.owner_of(code, 2) if code >= 0 else None
                )
                if owner == 1:
                    assert out.degraded is True
                    assert out.degraded_shards == (1,)
                    assert np.float32(out) == np.float32(ref_fe[i]), i
                else:
                    assert out.degraded is False
                    assert np.float32(out) == np.float32(ref_full[i]), i
            snap = router.health[1].snapshot()
            assert snap["failures"] >= 1
            assert router.health[0].snapshot()["failures"] == 0
        finally:
            close_fleet(servers[:1], router)

    def test_stalled_shard_hedged_then_shed_within_budget(self, rng):
        """A wedged (not dead) shard: the sub-request times out, is
        hedged once on a fresh connection, then shed — the request
        still answers inside its own budget, degraded FE-only."""
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        servers = build_fleet(lm, ds, 2)
        router = build_router(
            servers,
            lm,
            policy=RoutingPolicy(subrequest_timeout_s=0.6),
        )
        try:
            ids = user_ids(lm)
            rec = next(
                r for r in recs
                if r["metadataMap"]["userId"] in ids
                and ownership.owner_of(
                    ids.index(r["metadataMap"]["userId"]), 2
                ) == 1
            )
            # wedge shard 1's dispatcher (the donating-swap exclusion
            # lock: dispatch cannot run while it is held)
            gate = servers[1].serving_model.dispatch_lock
            gate.acquire()
            try:
                t0 = time.perf_counter()
                out = router.score_record(rec)
                elapsed = time.perf_counter() - t0
            finally:
                gate.release()
            assert out.degraded is True and out.degraded_shards == (1,)
            assert elapsed < 5.0
            assert router.metrics.snapshot()["hedges"] >= 1
            # the shard recovers: the same record scores exact now
            out2 = router.score_record(rec)
            assert out2.degraded is False
        finally:
            close_fleet(servers, router)

    def test_all_shards_down_is_named_refusal(self, rng):
        recs = synth_records(rng, n=5)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        servers = build_fleet(lm, ds, 2)
        router = build_router(
            servers,
            lm,
            policy=RoutingPolicy(subrequest_timeout_s=0.4),
        )
        try:
            for srv in servers:
                srv.close()
            with pytest.raises(NoShardAvailable):
                router.score_record(recs[0])
            assert router.metrics.snapshot()["failed"] == 1
        finally:
            router.close()

    def test_circuit_breaker_skips_dead_shard_without_waiting(self, rng):
        """After fail_threshold consecutive failures the breaker opens:
        requests for that shard's entities degrade IMMEDIATELY (no
        timeout wait), until the cooldown admits a probe."""
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        servers = build_fleet(lm, ds, 2)
        router = build_router(
            servers,
            lm,
            policy=RoutingPolicy(
                subrequest_timeout_s=0.4,
                fail_threshold=2,
                cooldown_s=60.0,
                hedge=False,
            ),
        )
        try:
            servers[1].close()
            ids = user_ids(lm)
            owned = [
                r for r in recs
                if r["metadataMap"]["userId"] in ids
                and ownership.owner_of(
                    ids.index(r["metadataMap"]["userId"]), 2
                ) == 1
            ]
            for rec in owned[:2]:
                router.score_record(rec)  # trip the breaker
            assert not router.health[1].allow()
            t0 = time.perf_counter()
            out = router.score_record(owned[2])
            assert out.degraded is True
            assert time.perf_counter() - t0 < 0.2, (
                "an open breaker must shed without waiting out the "
                "sub-request budget"
            )
        finally:
            close_fleet(servers[:1], router)


def synthetic_bank_arrays(rng, *, scale=1.0, E=14, d_g=6, d_u=4):
    ids = sorted(f"user{i:02d}" for i in range(E))
    fe_w = (rng.standard_normal(d_g) * scale).astype(np.float32)
    re_w = (rng.standard_normal((E, d_u)) * scale).astype(np.float32)
    return ids, fe_w, re_w


def synthetic_fleet(arrays, n_shards, *, stagers=None):
    """In-memory fleet from raw arrays (bank_from_arrays) — the
    swap-under-traffic rig: ``stagers[s]`` builds shard ``s``'s NEXT
    generation bank on stage_swap."""
    from photon_ml_tpu.utils.index_map import IndexMap

    ids, fe_w, re_w = arrays
    d_g, d_u = fe_w.shape[0], re_w.shape[1]
    widths = {"g": 4, "u": 4}
    imaps = {
        "g": IndexMap({f"g{j}\t": j for j in range(d_g)}),
        "u": IndexMap({f"u{j}\t": j for j in range(d_u)}),
    }

    def build(s, n, fe, re):
        return bank_from_arrays(
            fixed=[("global", "g", fe)],
            random=[("per-user", "userId", "u", re, ids)],
            shard_widths=widths,
            index_maps=imaps,
            entity_shard=(s, n),
        )

    servers = []
    for s in range(n_shards):
        sm = ServingModel(
            build(s, n_shards, fe_w, re_w),
            ServingPrograms(LADDER),
            partial=True,
            entity_shard=(s, n_shards),
        )
        stager = None
        if stagers is not None:
            stager = stagers(s, sm, build)
        servers.append(
            ShardServer(
                sm, SHARDS, (s, n_shards), stager=stager,
                has_response=False,
            ).start()
        )
    return servers, build, widths


def synthetic_records(rng, ids, n=30, d_g=6, d_u=4):
    recs = []
    for i in range(n):
        recs.append({
            "uid": f"q{i}",
            "metadataMap": {"userId": ids[i % len(ids)]},
            "features": [
                {"name": f"g{j}", "term": "",
                 "value": float(rng.standard_normal())}
                for j in range(3)
            ],
            "userFeatures": [
                {"name": f"u{j}", "term": "",
                 "value": float(rng.standard_normal())}
                for j in range(2)
            ],
            "offset": float(rng.normal() * 0.1),
        })
    return recs


def reference_router(arrays, widths):
    """A single-shard fleet as the bitwise oracle for synthetic banks
    (the single-server path is itself pinned bitwise vs the batch
    scorer by tests/test_serving.py)."""
    ids, _fe, _re = arrays
    servers, _build, _w = synthetic_fleet(arrays, 1)
    router = ShardRouter(
        [("127.0.0.1", servers[0].port)],
        entity_ids={"userId": ids},
        shard_configs=SHARDS,
        cache_entries=0,
    )
    router.connect()
    return servers, router


class TestHotEntityCache:
    def test_replay_serves_from_cache_bitwise_with_zero_fanout(
        self, rng
    ):
        """Zipf head traffic: the second pass over identical records
        answers entirely from the generation-keyed cache — bitwise the
        cold pass, fan-out 0."""
        arrays = synthetic_bank_arrays(rng)
        ids = arrays[0]
        servers, _build, _w = synthetic_fleet(arrays, 2)
        router = build_router_synth(servers, ids)
        try:
            recs = synthetic_records(rng, ids)
            cold = [router.score_record(r) for r in recs]
            warm = [router.score_record(r) for r in recs]
            assert np.array_equal(
                np.asarray(cold, np.float32),
                np.asarray(warm, np.float32),
            ), "a cache hit must be bitwise the cold path"
            assert all(w.cache_hit and w.fanout == 0 for w in warm)
            snap = router.cache.snapshot()
            assert snap["hits"] >= len(recs)
        finally:
            close_fleet(servers, router)

    def test_degraded_responses_never_populate_the_cache(self, rng):
        arrays = synthetic_bank_arrays(rng)
        ids = arrays[0]
        servers, _build, _w = synthetic_fleet(arrays, 2)
        router = build_router_synth(
            servers, ids,
            policy=RoutingPolicy(subrequest_timeout_s=0.4, hedge=False),
        )
        try:
            servers[1].close()
            rec = next(
                r for r in synthetic_records(rng, ids)
                if ownership.owner_of(
                    ids.index(r["metadataMap"]["userId"]), 2
                ) == 1
            )
            out1 = router.score_record(rec)
            assert out1.degraded
            out2 = router.score_record(rec)
            assert out2.degraded and not out2.cache_hit
            assert router.cache.snapshot()["hits"] == 0
        finally:
            close_fleet(servers[:1], router)

    def test_swap_commit_purges_cache_and_gen1_never_serves_gen2(
        self, rng
    ):
        """The invalidation contract across a DONATED hot swap (same
        shapes, new values — exactly the case entity padding
        preserves): a record cached at gen 1 must answer gen 2's score
        (bitwise the gen-2 oracle) right after the two-step flip, and
        the purge is atomic at commit."""
        rng2 = np.random.default_rng(rng.integers(1 << 30))
        arrays1 = synthetic_bank_arrays(rng, scale=1.0)
        ids = arrays1[0]
        fe2 = (np.asarray(arrays1[1]) * -2.0).astype(np.float32)
        re2 = (np.asarray(arrays1[2]) * 0.5).astype(np.float32)
        arrays2 = (ids, fe2, re2)

        def stagers(s, sm, build):
            def stage(obj):
                n = sm.entity_shard[1]
                return sm.prepare_swap_bank(
                    build(s, n, fe2, re2)
                )

            return stage

        servers, build, widths = synthetic_fleet(
            arrays1, 2, stagers=stagers
        )
        router = build_router_synth(servers, ids)
        oracle1_servers, oracle1 = reference_router(arrays1, widths)
        oracle2_servers, oracle2 = reference_router(arrays2, widths)
        try:
            recs = synthetic_records(rng2, ids)
            ref1 = [oracle1.score_record(r) for r in recs]
            ref2 = [oracle2.score_record(r) for r in recs]
            cold = [router.score_record(r) for r in recs]
            assert np.array_equal(
                np.asarray(cold, np.float32),
                np.asarray(ref1, np.float32),
            )
            warm = [router.score_record(r) for r in recs]
            assert all(w.cache_hit for w in warm)
            res = router.coordinate_swap("synthetic")
            assert res["ok"], res
            assert res["generation"] == 2
            assert res["cache_purged"] > 0, (
                "commit must purge the stale generation's entries"
            )
            after = [router.score_record(r) for r in recs]
            assert all(a.generation == 2 for a in after)
            assert not any(a.cache_hit for a in after), (
                "a gen-1 entry must never answer a gen-2 request"
            )
            assert np.array_equal(
                np.asarray(after, np.float32),
                np.asarray(ref2, np.float32),
            ), "post-swap routed scores must be bitwise the gen-2 oracle"
            assert not np.array_equal(
                np.asarray(after, np.float32),
                np.asarray(cold, np.float32),
            ), "the two generations must actually differ"
            # and the new generation caches again
            warm2 = [router.score_record(r) for r in recs]
            assert all(w.cache_hit for w in warm2)
            assert np.array_equal(
                np.asarray(warm2, np.float32),
                np.asarray(ref2, np.float32),
            )
        finally:
            close_fleet(servers, router)
            close_fleet(oracle1_servers, oracle1)
            close_fleet(oracle2_servers, oracle2)

    def test_failed_stage_aborts_fleet_wide_nobody_flips(self, rng):
        """Two-step flip, phase-1 failure: shard 1 refuses its stage —
        shard 0's parked generation is aborted, every shard still
        serves (and reports) generation 1, scores unchanged bitwise."""
        from photon_ml_tpu.serving.swap import SwapResult

        arrays = synthetic_bank_arrays(rng)
        ids = arrays[0]

        def stagers(s, sm, build):
            if s == 0:
                def stage_ok(obj):
                    n = sm.entity_shard[1]
                    return sm.prepare_swap_bank(
                        build(s, n, arrays[1], arrays[2])
                    )

                return stage_ok

            def stage_fail(obj):
                return SwapResult(
                    ok=False, generation=1, error="poisoned artifact"
                )

            return stage_fail

        servers, _build, _w = synthetic_fleet(arrays, 2, stagers=stagers)
        router = build_router_synth(servers, ids)
        try:
            recs = synthetic_records(rng, ids, n=8)
            before = [router.score_record(r) for r in recs]
            res = router.coordinate_swap("synthetic")
            assert res["ok"] is False and res["phase"] == "stage"
            assert res["failed_shard"] == 1
            assert router.generation == 1
            # shard 0's parked bank was aborted, not left to leak into
            # a later commit
            assert servers[0].serving_model._prepared is None
            after = [router.score_record(r) for r in recs]
            assert np.array_equal(
                np.asarray(before, np.float32),
                np.asarray(after, np.float32),
            )
            assert all(a.generation == 1 for a in after)
        finally:
            close_fleet(servers, router)

    def test_cache_unit_lru_and_generation_keying(self):
        cache = HotEntityCache(max_entries=2)
        cache.put((1, FE_SLOT, b"a"), 1.5)
        cache.put((1, "re", b"b"), 2.5)
        assert cache.get((1, FE_SLOT, b"a")) == 1.5
        cache.put((1, "re", b"c"), 3.5)  # evicts LRU ((1,"re",b"b"))
        assert cache.get((1, "re", b"b")) is None
        assert cache.get((2, FE_SLOT, b"a")) is None, (
            "generation is part of the key"
        )
        assert cache.purge_other_generations(2) == 2
        assert cache.get((1, FE_SLOT, b"a")) is None
        snap = cache.snapshot()
        assert snap["entries"] == 0 and snap["purged"] == 2
        off = HotEntityCache(max_entries=0)
        off.put((1, FE_SLOT, b"a"), 1.0)
        assert off.get((1, FE_SLOT, b"a")) is None
        assert not off.enabled


def build_router_synth(servers, ids, **kw):
    kw.setdefault("shard_configs", SHARDS)
    router = ShardRouter(
        [("127.0.0.1", srv.port) for srv in servers],
        entity_ids={"userId": ids},
        **kw,
    )
    router.connect()
    return router


# -- interleaving schedule families (satellite 3) -----------------------------
#
# The router fan-out/cache/swap plane under the deterministic scheduler
# (photon_ml_tpu/testing/interleave.py): fake in-process shards whose
# handlers are pure host math, transports that resolve futures on
# cooperative threads — so every lock acquisition, future wait and
# virtual timeout in the REAL ShardRouter is a schedulable preemption
# point. Invariants over every seeded schedule: every score call
# reaches exactly one terminal outcome, zero deadlocks, and every
# emitted margin is bitwise the expected value FOR ITS GENERATION —
# which is precisely "the cache never serves cross-generation".

IDS16 = sorted(f"user{i:02d}" for i in range(16))


class _FakeShard:
    """One shard's control + scoring plane as pure host f32 math: fe
    and the per-entity term are deterministic functions of (record,
    generation), so the verifier can recompute the exact expected
    margin for whatever generation a response claims."""

    def __init__(self, index: int, count: int):
        self.index = index
        self.count = count
        self.generation = 1
        self.staged = None
        self.dead = False
        self._lock = threading.Lock()

    @staticmethod
    def fe_of(record, gen: int) -> np.float32:
        return np.float32(
            np.float32(gen * 1.25)
            + np.float32(record["features"][0]["value"])
        )

    @staticmethod
    def term_of(record, code: int, gen: int) -> np.float32:
        return np.float32(
            np.float32(gen * 10.0 + code)
            + np.float32(record["userFeatures"][0]["value"])
        )

    def handle(self, obj):
        if self.dead:
            raise TransportError("shard process gone")
        op = obj.get("op")
        uid = obj.get("uid")
        with self._lock:
            gen = self.generation
            if op == "topology":
                return {
                    "uid": uid, "status": "ok",
                    "shard_index": self.index,
                    "shard_count": self.count,
                    "rule": ownership.OWNERSHIP_RULE,
                    "generation": gen,
                    "entries": [["re", "per-user", ["userId"], "u"]],
                }
            if op == "stage_swap":
                self.staged = gen + 1
                return {"uid": uid, "status": "ok", "ok": True,
                        "generation": self.staged, "error": ""}
            if op == "commit_swap":
                if self.staged is None:
                    return {"uid": uid, "status": "error", "ok": False,
                            "generation": gen,
                            "error": "nothing staged"}
                self.generation = self.staged
                self.staged = None
                return {"uid": uid, "status": "ok", "ok": True,
                        "generation": self.generation, "error": ""}
            if op == "abort_swap":
                had = self.staged is not None
                self.staged = None
                return {"uid": uid, "status": "ok", "aborted": had}
        entity = (obj.get("metadataMap") or {}).get("userId")
        code = IDS16.index(entity) if entity in IDS16 else -1
        term = 0.0
        if code >= 0 and ownership.owner_of(code, self.count) == self.index:
            term = float(self.term_of(obj, code, gen))
        return {
            "uid": obj["uid"], "status": "ok", "partial": True,
            "fe": float(self.fe_of(obj, gen)),
            "terms": {"per-user": term},
            "generation": gen, "degraded": False,
        }


class _FakeTransport:
    """Resolves each request's future on a (cooperative) thread, so the
    shard handler interleaves with router code under the scheduler."""

    closed = False

    def __init__(self, shard: _FakeShard):
        self.shard = shard

    def send_request(self, obj):
        from concurrent.futures import Future

        fut = Future()
        snapshot = dict(obj)

        def work():
            try:
                fut.set_result(self.shard.handle(snapshot))
            except BaseException as e:
                if not fut.done():
                    fut.set_exception(TransportError(str(e)))

        threading.Thread(target=work, daemon=True).start()
        return fut

    def request(self, obj, timeout_s):
        import concurrent.futures as cf

        fut = self.send_request(obj)
        try:
            return fut.result(timeout=max(timeout_s, 0.001))
        except (TimeoutError, cf.TimeoutError):
            raise TransportError("timeout") from None

    def abandon(self, uid):
        pass

    def close(self):
        pass


def _interleave_record(i: int) -> dict:
    return {
        "uid": f"iv{i}",
        "metadataMap": {"userId": IDS16[i % len(IDS16)]},
        "features": [{"name": "g0", "term": "",
                      "value": 0.125 * (i % 7)}],
        "userFeatures": [{"name": "u0", "term": "",
                          "value": 0.25 * (i % 5)}],
        "offset": 0.0,
    }


def _expected_margin(record, gen: int, *, fe_only: bool) -> np.float32:
    entity = record["metadataMap"]["userId"]
    code = IDS16.index(entity)
    total = _FakeShard.fe_of(record, gen)
    term = (
        np.float32(0.0) if fe_only
        else _FakeShard.term_of(record, code, gen)
    )
    total = np.float32(total + term)
    return np.float32(total + np.float32(record["offset"]))


class TestRouterInterleave:
    N_SHARDS = 2

    def _scenario(self, sched, *, kill_shard: bool):
        from photon_ml_tpu.serving import ServingError

        results = []
        failures = []
        submitted = [0]
        with sched.patched():
            shards = [
                _FakeShard(i, self.N_SHARDS)
                for i in range(self.N_SHARDS)
            ]
            router = ShardRouter(
                transport_factory=lambda i: _FakeTransport(shards[i]),
                num_shards=self.N_SHARDS,
                entity_ids={"userId": IDS16},
                shard_configs=SHARDS,
                policy=RoutingPolicy(
                    subrequest_timeout_s=1.0, cooldown_s=0.5
                ),
                cache_entries=64,
            )
            def scorer(base):
                def body():
                    # repeats on purpose: the cache plane must race the
                    # swap commit
                    for k in [0, 1, 2, 0, 1, 2]:
                        rec = _interleave_record(base + k)
                        submitted[0] += 1
                        try:
                            results.append(
                                (rec, router.score_record(rec))
                            )
                        except ServingError as e:
                            results.append((rec, e))
                        except BaseException as e:
                            failures.append(e)
                            return

                return body

            def swapper():
                res = router.coordinate_swap("synthetic")
                results.append(("swap", res))

            def driver():
                # connect + spawn on a SCHEDULED task: the harness's
                # unmanaged main thread never parks, so waits on the
                # fake transports' futures must happen here
                router.connect()
                workers = [
                    threading.Thread(
                        target=scorer(4 * t), name=f"scorer{t}"
                    )
                    for t in range(3)
                ]
                workers.append(
                    threading.Thread(target=swapper, name="swapper")
                )
                if kill_shard:
                    def killer():
                        shards[1].dead = True

                    workers.append(
                        threading.Thread(target=killer, name="killer")
                    )
                for w in workers:
                    w.start()

            sched.spawn(driver, name="driver")
            sched.run()

        def verify():
            from photon_ml_tpu.serving import ServingError

            assert not failures, failures[:2]
            outcomes = [r for r in results if r[0] != "swap"]
            assert len(outcomes) == submitted[0], (
                "every score call must reach exactly one terminal "
                "outcome"
            )
            for rec, out in outcomes:
                if isinstance(out, ServingError):
                    continue  # a named refusal IS terminal
                assert out.generation in (1, 2), out.generation
                entity = rec["metadataMap"]["userId"]
                code = IDS16.index(entity)
                owner = ownership.owner_of(code, self.N_SHARDS)
                want_exact = _expected_margin(
                    rec, out.generation, fe_only=False
                )
                want_fe = _expected_margin(
                    rec, out.generation, fe_only=True
                )
                if out.degraded:
                    assert kill_shard and owner == 1, (
                        "only the killed shard's entities may degrade"
                    )
                    assert np.float32(out) == want_fe, (
                        rec["uid"], float(out), float(want_fe),
                        out.generation,
                    )
                else:
                    # bitwise-correct FOR ITS GENERATION — a cached
                    # gen-1 slot leaking under gen 2 (or vice versa)
                    # matches neither generation's expectation
                    assert np.float32(out) == want_exact, (
                        rec["uid"], float(out), float(want_exact),
                        out.generation,
                    )
            swaps = [r[1] for r in results if r[0] == "swap"]
            if swaps and swaps[0]["ok"] and not kill_shard:
                assert all(s.generation == 2 for s in shards)

        return verify

    def test_fanout_cache_swap_schedules(self):
        from photon_ml_tpu.testing.interleave import explore

        explore(
            lambda sched: self._scenario(sched, kill_shard=False),
            seeds=range(10),
        )

    def test_fanout_cache_swap_schedules_with_shard_death(self):
        from photon_ml_tpu.testing.interleave import explore

        explore(
            lambda sched: self._scenario(sched, kill_shard=True),
            seeds=range(10, 20),
        )


class TestDriverValidation:
    def _params(self, **over):
        from photon_ml_tpu.cli.serving_driver import ServingParams

        base = dict(
            game_model_input_dir="m",
            output_dir="o",
            feature_shards=SHARDS,
            frontend_port=0,
            offheap_indexmap_dir="maps",
            request_nnz_width="4",
        )
        base.update(over)
        return ServingParams(**base)

    def test_shard_mode_validation_rules(self):
        self._params(shard_index=0, shard_count=2).validate()
        with pytest.raises(ValueError, match="go together"):
            self._params(shard_index=0).validate()
        with pytest.raises(ValueError, match="shard-index < shard-count"):
            self._params(shard_index=2, shard_count=2).validate()
        with pytest.raises(ValueError, match="frontend-port"):
            self._params(
                shard_index=0, shard_count=2, frontend_port=None,
                request_paths=["t"],
            ).validate()
        with pytest.raises(ValueError, match="registry"):
            self._params(
                shard_index=0, shard_count=2,
                game_model_input_dir="", registry_dir="r",
            ).validate()
        with pytest.raises(ValueError, match="two-step"):
            self._params(
                shard_index=0, shard_count=2, swap_model_dir="g2",
                swap_after_requests=5,
            ).validate()

    def test_router_mode_validation_rules(self):
        self._params(
            shard_servers="127.0.0.1:1,127.0.0.1:2",
            frontend_port=None, request_paths=["t"],
        ).validate()
        with pytest.raises(ValueError, match="not both"):
            self._params(
                shard_servers="h:1", shard_index=0, shard_count=1,
            ).validate()
        with pytest.raises(ValueError, match="frontend"):
            self._params(
                shard_servers="h:1", request_paths=["t"],
            ).validate()
        with pytest.raises(ValueError, match="request-paths"):
            self._params(
                shard_servers="h:1", frontend_port=None,
            ).validate()
        with pytest.raises(ValueError, match="entity"):
            self._params(
                shard_servers="h:1", frontend_port=None,
                request_paths=["t"], game_model_input_dir="",
            ).validate()
        p = self._params(
            shard_servers="hostA:12, hostB:13",
            frontend_port=None, request_paths=["t"],
        )
        assert p.shard_addresses == [("hostA", 12), ("hostB", 13)]


@pytest.mark.slow
class TestShardRoutingDriverEndToEnd:
    def test_router_replay_bitwise_vs_single_server_across_processes(
        self, tmp_path, rng
    ):
        """The operating story: save a real FE+RE artifact, boot N=2
        shard-server subprocesses (--shard-index/--shard-count), replay
        the trace through the router driver (--shard-servers), and
        diff the scores artifact bitwise against the single-server
        replay of the same trace. Then SIGTERM the fleet: clean drains,
        0 cold compiles on any shard."""
        from tests.conftest import game_example_schema

        from photon_ml_tpu.game.model_io import (
            LoadedGameModel as LGM,
            save_loaded_game_model,
        )
        from photon_ml_tpu.io.avro_codec import (
            read_avro_records,
            write_container,
        )
        from photon_ml_tpu.io.name_term_list import (
            save_name_and_term_feature_sets,
        )

        lm = LGM()
        lm.fixed_effects["global"] = (
            "g", {f"g{j}\t": float(rng.normal()) for j in range(5)},
        )
        lm.random_effects["per-user"] = (
            "userId", "u",
            {
                f"user{e}": {
                    f"u{j}\t": float(rng.normal()) for j in range(3)
                }
                for e in range(6)
            },
        )
        model_dir = save_loaded_game_model(lm, str(tmp_path / "model"))
        nt_dir = str(tmp_path / "nt")
        save_name_and_term_feature_sets(
            {
                "features": {f"g{j}\t" for j in range(5)},
                "userFeatures": {f"u{j}\t" for j in range(3)},
            },
            nt_dir,
        )
        recs = synth_records(rng, n=50, n_users=7)
        trace = tmp_path / "trace"
        write_container(
            str(trace / "part-0.avro"), game_example_schema(),
            [
                {
                    k: r[k]
                    for k in ("uid", "response", "metadataMap",
                              "features", "userFeatures")
                }
                for r in recs
            ],
        )
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        common = [
            "--feature-shard-id-to-feature-section-keys-map",
            "g:features|u:userFeatures",
            "--feature-name-and-term-set-path", nt_dir,
            "--request-nnz-width", "g:8|u:8",
            "--ladder", "1,8",
        ]
        ref_out = str(tmp_path / "ref-out")
        r = subprocess.run(
            [
                sys.executable, "-m",
                "photon_ml_tpu.cli.serving_driver",
                "--game-model-input-dir", model_dir,
                "--output-dir", ref_out,
                "--request-paths", str(trace),
            ] + common,
            cwd=REPO, env=env, capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        procs = []
        try:
            for s in range(2):
                out = str(tmp_path / f"shard{s}")
                procs.append((out, subprocess.Popen(
                    [
                        sys.executable, "-m",
                        "photon_ml_tpu.cli.serving_driver",
                        "--game-model-input-dir", model_dir,
                        "--output-dir", out,
                        "--frontend-port", "0",
                        "--shard-index", str(s),
                        "--shard-count", "2",
                    ] + common,
                    cwd=REPO, env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, text=True,
                )))
            ports = []
            for out, p in procs:
                fj = os.path.join(out, "frontend.json")
                assert _wait_until(
                    lambda: os.path.exists(fj), timeout=120
                ), "shard-server never published its port"
                meta = json.load(open(fj))
                ports.append(meta["port"])
                assert meta["shard"]["shard_count"] == 2
                assert meta["shard"]["rule"] == ownership.OWNERSHIP_RULE
                assert meta["shard"]["partial"] is True
            rout = str(tmp_path / "router-out")
            r = subprocess.run(
                [
                    sys.executable, "-m",
                    "photon_ml_tpu.cli.serving_driver",
                    "--game-model-input-dir", model_dir,
                    "--output-dir", rout,
                    "--request-paths", str(trace),
                    "--mode", "open", "--concurrency", "4",
                    "--shard-servers",
                    ",".join(f"127.0.0.1:{p}" for p in ports),
                    "--feature-shard-id-to-feature-section-keys-map",
                    "g:features|u:userFeatures",
                ],
                cwd=REPO, env=env, capture_output=True, text=True,
            )
            assert r.returncode == 0, (
                r.stdout[-3000:] + r.stderr[-2000:]
            )

            def scores(d):
                return {
                    x["uid"]: x["predictionScore"]
                    for x in read_avro_records(os.path.join(d, "scores"))
                }

            ref, got = scores(ref_out), scores(rout)
            assert set(ref) == set(got)
            assert not [
                u for u in ref
                if np.float32(ref[u]) != np.float32(got[u])
            ], "routed scores must be bitwise the single-server replay"
            m = json.load(open(os.path.join(rout, "metrics.json")))
            assert m["mode"] == "router"
            assert m["outcomes"] == {"ok": len(recs)}
            assert m["routing"]["shards"] == 2
            for out, p in procs:
                p.send_signal(signal.SIGTERM)
            for out, p in procs:
                assert p.wait(timeout=60) == 0
                sm = json.load(open(os.path.join(out, "metrics.json")))
                assert sm["programs"]["cold_dispatch_compiles"] == 0
                assert sm["leaked_connections"] == 0
        finally:
            for _out, p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate(timeout=30)
