"""Persistent tile-schedule cache (ops/schedule_cache.py): disk-tier
hit/miss/corruption behavior, bit-identical reloads, the two bounded
in-memory LRU tiers in front of it, and the multi-host write-once /
read-many protocol."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from photon_ml_tpu.data.batch import make_sparse_batch
from photon_ml_tpu.ops import schedule_cache as sc
from photon_ml_tpu.ops import tiled_sparse as ts
from photon_ml_tpu.ops.tiled_sparse import TileParams, tiled_batch_from_sparse

PARAMS = TileParams(s_hi=8, s_lo=8, chunk=32)  # window 64, tiny for tests


@pytest.fixture(autouse=True)
def _fresh_cache_state():
    """Process-global cache state must not leak between tests."""
    sc.reset_stats()
    ts._TILED_CACHE.clear()
    ts._SHARDED_CACHE.clear()
    yield
    sc.reset_stats()
    ts._TILED_CACHE.clear()
    ts._SHARDED_CACHE.clear()


def _coo(rng, n_entries=400, out_space=512, in_space=512):
    rows = rng.integers(0, out_space, size=n_entries).astype(np.int64)
    feats = rng.integers(0, in_space, size=n_entries).astype(np.int64)
    vals = rng.normal(size=n_entries).astype(np.float32)
    vals[vals == 0] = 1.0
    return rows, feats, vals


def _build(rows, feats, vals, *, feat_sorted=False, blocks=8):
    return ts._build_schedule_np(
        rows, feats, vals, params=PARAMS,
        sort_by_feature_block=feat_sorted, num_out_blocks=blocks,
    )


def random_problem(rng, n=100, d=150, k=6):
    rows, labels = [], []
    for _ in range(n):
        nnz = int(rng.integers(1, k + 1))
        ix = rng.choice(d, size=nnz, replace=False).tolist()
        vs = rng.normal(size=nnz).tolist()
        labels.append(float(rng.uniform() > 0.5))
        rows.append((ix, vs))
    return make_sparse_batch(rows, labels), d


def _assert_schedules_equal(a, b):
    for i, (x, y) in enumerate(zip(a, b)):
        xa, ya = np.asarray(x), np.asarray(y)
        assert xa.dtype == ya.dtype, f"array {i} dtype"
        assert xa.shape == ya.shape, f"array {i} shape"
        assert np.array_equal(xa, ya), f"array {i} content"


class TestDiskTier:
    def test_miss_then_hit_roundtrip(self, rng, tmp_path):
        rows, feats, vals = _coo(rng)
        with sc.cache_scope(str(tmp_path)):
            fresh = _build(rows, feats, vals)
            s1 = sc.stats()
            assert (s1.misses, s1.builds, s1.stores) == (1, 1, 1)
            reloaded = _build(rows, feats, vals)
            s2 = sc.stats()
        assert s2.hits == 1 and s2.builds == 1  # no second build
        _assert_schedules_equal(fresh, reloaded)

    def test_key_separates_passes_and_params(self, rng, tmp_path):
        rows, feats, vals = _coo(rng)
        digest = sc.content_digest(rows, feats, vals)
        k1 = sc.schedule_key(digest, PARAMS, False, 8)
        assert k1 == sc.schedule_key(digest, PARAMS, False, 8)
        assert k1 != sc.schedule_key(digest, PARAMS, True, 8)
        assert k1 != sc.schedule_key(digest, PARAMS, False, 9)
        import dataclasses

        other = dataclasses.replace(PARAMS, chunk=64)
        assert k1 != sc.schedule_key(digest, other, False, 8)
        # content participates: one flipped value changes the digest
        vals2 = vals.copy()
        vals2[0] += 1.0
        assert digest != sc.content_digest(rows, feats, vals2)

    def test_version_bump_falls_back_to_rebuild(
        self, rng, tmp_path, monkeypatch
    ):
        rows, feats, vals = _coo(rng)
        with sc.cache_scope(str(tmp_path)):
            _build(rows, feats, vals)
            monkeypatch.setattr(sc, "SCHEDULE_CACHE_VERSION", 999)
            _build(rows, feats, vals)
            s = sc.stats()
        # the bumped version neither hit the old artifact nor crashed:
        # it rebuilt and stored under the new version namespace
        assert s.hits == 0 and s.builds == 2 and s.stores == 2

    def test_corrupted_artifact_falls_back_to_rebuild(self, rng, tmp_path):
        rows, feats, vals = _coo(rng)
        digest = sc.content_digest(rows, feats, vals)
        key = sc.schedule_key(digest, PARAMS, False, 8)
        with sc.cache_scope(str(tmp_path)):
            fresh = _build(rows, feats, vals)
            # flip bytes inside the artifact (within the spot-checksum
            # window) — the damaged artifact must be rejected, not served
            path = os.path.join(
                sc._artifact_dir(str(tmp_path), key), "vals.npy"
            )
            with open(path, "r+b") as f:
                f.seek(200)
                f.write(b"\xff" * 32)
            rebuilt = _build(rows, feats, vals)
            s = sc.stats()
        assert s.corrupt >= 1 and s.builds == 2
        _assert_schedules_equal(fresh, rebuilt)

    def test_truncated_artifact_rejected(self, rng, tmp_path):
        rows, feats, vals = _coo(rng)
        digest = sc.content_digest(rows, feats, vals)
        key = sc.schedule_key(digest, PARAMS, False, 8)
        with sc.cache_scope(str(tmp_path)):
            _build(rows, feats, vals)
            path = os.path.join(
                sc._artifact_dir(str(tmp_path), key), "in_pos.npy"
            )
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(size // 2)
            assert sc.load_schedule(str(tmp_path), key) is None

    def test_bit_identical_tiled_batch_on_reload(self, rng, tmp_path):
        batch, d = random_problem(rng)
        tb_nocache = tiled_batch_from_sparse(batch, d, params=PARAMS)
        with sc.cache_scope(str(tmp_path)):
            tb_cold = tiled_batch_from_sparse(batch, d, params=PARAMS)
            tb_warm = tiled_batch_from_sparse(batch, d, params=PARAMS)
            s = sc.stats()
        assert s.hits == 2  # z + g pass both reloaded
        for tb in (tb_cold, tb_warm):
            _assert_schedules_equal(tb_nocache.z_sched, tb.z_sched)
            _assert_schedules_equal(tb_nocache.g_sched, tb.g_sched)
        assert tb_warm.meta == tb_nocache.meta

    def test_cache_off_by_default(self, rng):
        assert sc.resolve_cache_dir() is None  # hermetic under pytest
        rows, feats, vals = _coo(rng)
        _build(rows, feats, vals)
        s = sc.stats()
        assert (s.hits, s.misses, s.stores) == (0, 0, 0)
        assert s.builds == 1  # the build seam still counts

    def test_scope_overrides_configure(self, tmp_path):
        try:
            sc.configure(str(tmp_path / "configured"))
            assert sc.resolve_cache_dir() == str(tmp_path / "configured")
            with sc.cache_scope(str(tmp_path / "scoped")):
                assert sc.resolve_cache_dir() == str(tmp_path / "scoped")
            sc.configure("")  # explicit off beats the env var
            assert sc.resolve_cache_dir() is None
        finally:
            sc.configure(None)


class TestMemoryTiers:
    def test_lru_hit_refreshes_and_eviction_order(self):
        lru = sc.ScheduleLRU(2)
        lru.put("a", 1)
        lru.put("b", 2)
        assert lru.get("a") == 1  # refresh "a" -> "b" is now LRU
        lru.put("c", 3)
        assert lru.get("b") is None  # evicted
        assert lru.get("a") == 1 and lru.get("c") == 3
        assert len(lru) == 2

    def test_interleaved_tiled_and_sharded_build_once(self, rng):
        """Regression (ADVICE.md round 5): interleaving ensure_tiled and
        ensure_tiled_sharded must not evict each other's schedules — each
        layout is built exactly once per process."""
        from photon_ml_tpu.ops.tiled_sparse import (
            ensure_tiled,
            ensure_tiled_sharded,
        )
        from photon_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh

        batch, d = random_problem(rng)
        mesh = make_mesh((2,), (DATA_AXIS,), devices=jax.devices()[:2])
        ensure_tiled(batch, d, params=PARAMS)
        ensure_tiled_sharded(batch, d, mesh, params=PARAMS)
        builds_after_first = sc.stats().builds
        assert builds_after_first > 0
        for _ in range(3):
            ensure_tiled(batch, d, params=PARAMS)
            ensure_tiled_sharded(batch, d, mesh, params=PARAMS)
        assert sc.stats().builds == builds_after_first

    def test_sharded_pressure_does_not_evict_tiled(self, rng):
        """Several sharded conversions (> the sharded LRU bound) while a
        tiled conversion stays live: the tiled entry must survive."""
        from photon_ml_tpu.ops.tiled_sparse import (
            ensure_tiled,
            ensure_tiled_sharded,
        )
        from photon_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh

        mesh = make_mesh((2,), (DATA_AXIS,), devices=jax.devices()[:2])
        tiled_batch, d = random_problem(rng, n=60)
        ensure_tiled(tiled_batch, d, params=PARAMS)
        builds_tiled = sc.stats().builds
        others = [random_problem(rng, n=40 + 8 * i)[0] for i in range(3)]
        for b in others:
            ensure_tiled_sharded(b, d, mesh, params=PARAMS)
        ensure_tiled(tiled_batch, d, params=PARAMS)  # must still be cached
        # the re-ensure added no builds beyond the sharded conversions
        expected = builds_tiled + sum(
            1 for _ in others
        ) * 2 * 2  # 2 shards x (z+g) per sharded conversion
        assert sc.stats().builds == expected


_CHILD = r"""
import json, os, sys, time
import numpy as np

role, cache_dir = sys.argv[1], sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PHOTON_TILE_CACHE_WRITER"] = "1" if role == "writer" else "0"
os.environ["PHOTON_TILE_CACHE_WAIT_S"] = "60"
from photon_ml_tpu.ops import schedule_cache as sc
from photon_ml_tpu.ops import tiled_sparse as ts

rng = np.random.default_rng(7)
rows = rng.integers(0, 512, size=400).astype(np.int64)
feats = rng.integers(0, 512, size=400).astype(np.int64)
vals = rng.normal(size=400).astype(np.float32)
params = ts.TileParams(s_hi=8, s_lo=8, chunk=32)
if role == "writer":
    time.sleep(1.0)  # force the reader to actually wait
with sc.cache_scope(cache_dir):
    arrs = ts._build_schedule_np(
        rows, feats, vals, params=params,
        sort_by_feature_block=False, num_out_blocks=8,
    )
import hashlib
h = hashlib.blake2b(digest_size=16)
for a in arrs:
    h.update(np.ascontiguousarray(a).tobytes())
print(json.dumps({
    "role": role,
    "digest": h.hexdigest(),
    "builds": sc.stats().builds,
    "stores": sc.stats().stores,
}))
"""


class TestMultiHost:
    def test_two_process_write_once_read_many(self, tmp_path):
        """Host 0 builds and writes the artifact exactly once; the other
        process waits for it and reads — zero builds on the reader."""
        cache_dir = str(tmp_path / "cache")
        os.makedirs(cache_dir)

        def launch(role):
            return subprocess.Popen(
                [sys.executable, "-c", _CHILD, role, cache_dir],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )

        reader = launch("reader")
        time.sleep(0.2)
        writer = launch("writer")
        out = {}
        for proc in (writer, reader):
            stdout, stderr = proc.communicate(timeout=120)
            assert proc.returncode == 0, stderr
            rec = json.loads(stdout.strip().splitlines()[-1])
            out[rec["role"]] = rec
        assert out["writer"]["builds"] == 1
        assert out["writer"]["stores"] == 1
        assert out["reader"]["builds"] == 0  # waited and read, never built
        assert out["reader"]["digest"] == out["writer"]["digest"]

    def test_reader_timeout_builds_locally_without_store(
        self, rng, tmp_path, monkeypatch
    ):
        monkeypatch.setenv(sc.ENV_WRITER, "0")
        monkeypatch.setenv(sc.ENV_WAIT_S, "0.2")
        rows, feats, vals = _coo(rng)
        with sc.cache_scope(str(tmp_path)):
            out = _build(rows, feats, vals)
        s = sc.stats()
        assert s.builds == 1 and s.stores == 0 and s.wait_s > 0
        assert len(out) == len(sc.SCHEDULE_ARRAY_NAMES)
