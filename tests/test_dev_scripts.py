"""dev-scripts/libsvm_text_to_trainingexample_avro.py: LibSVM -> Avro
conversion parity (reference dev-scripts converter used by the a1a
tutorial, README.md:226-229)."""

import importlib.util
import os


_SCRIPT = os.path.join(
    os.path.dirname(__file__), "..", "dev-scripts",
    "libsvm_text_to_trainingexample_avro.py",
)


def _load_converter():
    spec = importlib.util.spec_from_file_location("libsvm_to_avro", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


LIBSVM_TEXT = """\
+1 3:1 11:1 14:0.5
-1 1:2 5:1
# a comment line

0 7:1.5
"""


def test_convert_roundtrip(tmp_path):
    mod = _load_converter()
    src = tmp_path / "data.txt"
    src.write_text(LIBSVM_TEXT)
    out = tmp_path / "data.avro"
    count = mod.convert(str(src), str(out))
    assert count == 3

    from photon_ml_tpu.io.avro_codec import read_avro_records

    recs = list(read_avro_records(str(out)))
    assert [r["label"] for r in recs] == [1.0, 0.0, 0.0]
    # feature names are the literal LibSVM index tokens, terms empty
    assert recs[0]["features"] == [
        {"name": "3", "term": "", "value": 1.0},
        {"name": "11", "term": "", "value": 1.0},
        {"name": "14", "term": "", "value": 0.5},
    ]
    assert recs[1]["features"][0]["name"] == "1"


def test_convert_regression_keeps_labels(tmp_path):
    mod = _load_converter()
    src = tmp_path / "data.txt"
    src.write_text("2.5 1:1\n-3.25 2:1\n")
    out = tmp_path / "data.avro"
    assert mod.convert(str(src), str(out), regression=True) == 2

    from photon_ml_tpu.io.avro_codec import read_avro_records

    labels = [r["label"] for r in read_avro_records(str(out))]
    assert labels == [2.5, -3.25]


def test_converted_file_feeds_avro_input_format(tmp_path):
    """The converter's output trains through the AVRO input path."""
    mod = _load_converter()
    src = tmp_path / "data.txt"
    lines = []
    import numpy as np

    rng = np.random.default_rng(0)
    for i in range(40):
        label = 1 if rng.uniform() > 0.5 else -1
        feats = " ".join(
            f"{j + 1}:{rng.normal():.4f}" for j in range(5)
        )
        lines.append(f"{label} {feats}")
    src.write_text("\n".join(lines) + "\n")
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    mod.convert(str(src), str(train_dir / "part.avro"))

    from photon_ml_tpu.io.input_format import AvroInputDataFormat

    fmt = AvroInputDataFormat()
    loaded = fmt.load([str(train_dir)])
    assert loaded.batch.labels.shape[0] == 40
    assert set(np.asarray(loaded.batch.labels).tolist()) <= {0.0, 1.0}
    # 5 features + intercept
    assert loaded.num_features == 6
