"""Native mmap index store tests: build/open/lookup/reverse, partitioned
loader, duplicate rejection, scale smoke test, IndexMap interchangeability.
"""


import numpy as np
import pytest

from photon_ml_tpu.utils.index_map import feature_key
from photon_ml_tpu.utils.native_index import (
    NativeIndexStore,
    PartitionedIndexMap,
    build_partitioned_index,
    build_store,
)


class TestSingleStore:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "s.pidx")
        keys = [f"feat{i}\tterm{i % 3}" for i in range(100)]
        build_store(path, keys)
        store = NativeIndexStore(path)
        assert len(store) == 100
        for i, k in enumerate(keys):
            assert store.get_index(k) == i
            assert store.get_key(i) == k
        assert store.get_index("missing\t") == -1
        assert store.get_key(100) is None
        store.close()

    def test_batched_lookup(self, tmp_path):
        path = str(tmp_path / "s.pidx")
        keys = [f"k{i}" for i in range(50)]
        build_store(path, keys)
        store = NativeIndexStore(path)
        out = store.get_indices(["k3", "nope", "k49"])
        assert out.tolist() == [3, -1, 49]
        store.close()

    def test_duplicates_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            build_store(str(tmp_path / "d.pidx"), ["a", "b", "a"])

    def test_unicode_keys(self, tmp_path):
        path = str(tmp_path / "u.pidx")
        keys = ["café\trésumé", "日本語\t", "emoji🎉\tx"]
        build_store(path, keys)
        store = NativeIndexStore(path)
        for i, k in enumerate(keys):
            assert store.get_index(k) == i
            assert store.get_key(i) == k
        store.close()


class TestPartitionedIndex:
    def test_build_and_lookup(self, tmp_path):
        keys = [feature_key(f"f{i}", str(i % 7)) for i in range(500)]
        pm = build_partitioned_index(keys, str(tmp_path / "idx"), num_partitions=4)
        assert pm.size == 500
        for k in keys[::37]:
            i = pm.get_index(k)
            assert i >= 0
            assert pm.get_feature_name(i) == k
        assert pm.get_index("absent\t") == -1
        # global indices are a bijection onto [0, size)
        seen = {i for _, i in pm.items()}
        assert seen == set(range(500))
        pm.close()

    def test_interchangeable_with_index_map(self, tmp_path):
        """PartitionedIndexMap satisfies the IndexMap protocol used by the
        input formats (get_index / get_feature_name / size)."""
        from photon_ml_tpu.io.input_format import LibSVMInputDataFormat

        p = tmp_path / "data.txt"
        p.write_text("+1 1:1 3:2\n-1 2:1\n")
        fmt = LibSVMInputDataFormat(add_intercept=False)
        keys = [feature_key(str(i)) for i in range(3)]
        pm = build_partitioned_index(keys, str(tmp_path / "idx"), num_partitions=2)
        data = fmt.load(str(p), index_map=pm)
        assert data.num_features == 3
        pm.close()

    def test_scale_smoke(self, tmp_path):
        n = 200_000
        keys = (f"name{i}\tt{i % 13}" for i in range(n))
        pm = build_partitioned_index(keys, str(tmp_path / "big"), num_partitions=8)
        assert pm.size == n
        rng = np.random.default_rng(0)
        for i in rng.integers(0, n, size=200):
            k = f"name{i}\tt{i % 13}"
            gi = pm.get_index(k)
            assert gi >= 0 and pm.get_feature_name(gi) == k
        pm.close()


class TestFeatureIndexingJob:
    def test_avro_job(self, tmp_path, rng):
        from tests.test_glm_driver import synth_avro
        from photon_ml_tpu.cli.feature_indexing_driver import run_feature_indexing
        from photon_ml_tpu.utils.index_map import intercept_key

        train = tmp_path / "train"; train.mkdir()
        synth_avro(str(train / "p.avro"), rng, n=50)
        shard_dir = run_feature_indexing(
            [str(train)], str(tmp_path / "idx"), num_partitions=3
        )
        pm = PartitionedIndexMap(shard_dir)
        assert pm.size == 9  # f0..f7 + intercept
        assert pm.get_index(intercept_key()) >= 0
        pm.close()
