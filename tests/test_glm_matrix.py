"""The documented GLM test matrix: solver x regularization x family x data
condition, checked by composable validators instead of golden numbers.

Reference: photon-ml supervised/BaseGLMIntegTest.scala:34-69 (the matrix)
with ModelValidators (BinaryPredictionValidator, PredictionFiniteValidator,
MaximumDifferenceValidator) — SURVEY §4 takeaway (b)."""

import numpy as np
import pytest

from photon_ml_tpu.data.batch import make_dense_batch
from photon_ml_tpu.optim.config import RegularizationType
from photon_ml_tpu.optim.factory import OptimizerType
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.training import train_generalized_linear_model

D = 8
N = 400


# -- data conditions (SparkTestUtils generator analogs, fixed seeds) -------


def _benign_features(rng, n=N, d=D):
    return rng.normal(size=(n, d)).astype(np.float32)


def _gen(task: TaskType, rng, *, outliers: bool = False):
    """Numerically benign draw for each family; ``outliers`` injects a few
    large-magnitude rows (the 'outlier' generator analog)."""
    x = _benign_features(rng)
    w = np.linspace(-1.0, 1.0, D)
    z = x @ w
    if task == TaskType.LOGISTIC_REGRESSION:
        y = (1 / (1 + np.exp(-z)) > rng.uniform(size=N)).astype(np.float32)
    elif task == TaskType.LINEAR_REGRESSION:
        y = (z + 0.1 * rng.normal(size=N)).astype(np.float32)
    elif task == TaskType.POISSON_REGRESSION:
        y = rng.poisson(np.exp(np.clip(0.3 * z, -3, 3))).astype(np.float32)
    else:  # SVM
        y = (z > 0).astype(np.float32)
    if outliers:
        x[:4] *= 40.0
    return make_dense_batch(x, y)


# -- composable validators (ModelValidator analogs) ------------------------


def prediction_finite_validator(model, batch):
    assert np.all(np.isfinite(np.asarray(model.mean(batch)))), (
        "non-finite predictions"
    )


def coefficients_finite_validator(model, batch):
    assert np.all(np.isfinite(np.asarray(model.means))), (
        "non-finite coefficients"
    )


def binary_prediction_validator(model, batch):
    preds = np.asarray(model.predict_class(batch))
    assert set(np.unique(preds)).issubset({0.0, 1.0})


def classification_accuracy_validator(model, batch, floor=0.7):
    preds = np.asarray(model.predict_class(batch))
    acc = float((preds == np.asarray(batch.labels)).mean())
    assert acc >= floor, f"accuracy {acc} below {floor}"


def maximum_difference_validator(model, batch, max_diff=1.5):
    diff = np.abs(np.asarray(model.mean(batch)) - np.asarray(batch.labels))
    assert float(diff.mean()) <= max_diff, f"mean |pred-label| {diff.mean()}"


def nonnegative_prediction_validator(model, batch):
    assert np.all(np.asarray(model.mean(batch)) >= 0)


_VALIDATORS = {
    TaskType.LOGISTIC_REGRESSION: [
        prediction_finite_validator,
        coefficients_finite_validator,
        binary_prediction_validator,
        classification_accuracy_validator,
    ],
    TaskType.LINEAR_REGRESSION: [
        prediction_finite_validator,
        coefficients_finite_validator,
        maximum_difference_validator,
    ],
    TaskType.POISSON_REGRESSION: [
        prediction_finite_validator,
        coefficients_finite_validator,
        nonnegative_prediction_validator,
    ],
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: [
        prediction_finite_validator,
        coefficients_finite_validator,
        binary_prediction_validator,
        classification_accuracy_validator,
    ],
}

_TASKS = list(_VALIDATORS)
_REGS = [
    (RegularizationType.NONE, None),
    (RegularizationType.L2, None),
    (RegularizationType.L1, None),
    (RegularizationType.ELASTIC_NET, 0.5),
]
_OPTIMIZERS = [OptimizerType.LBFGS, OptimizerType.TRON]


def _excluded(task, opt, reg):
    """The factory's forbidden combos (OptimizerFactory.scala:49-86):
    TRON with any L1 component; TRON needs a Hessian (no SVM)."""
    if opt == OptimizerType.TRON and reg in (
        RegularizationType.L1, RegularizationType.ELASTIC_NET
    ):
        return True
    if (
        opt == OptimizerType.TRON
        and task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM
    ):
        return True
    return False


@pytest.mark.parametrize("opt", _OPTIMIZERS, ids=lambda o: o.name)
@pytest.mark.parametrize(
    "reg,alpha", _REGS, ids=[r.name for r, _ in _REGS]
)
@pytest.mark.parametrize("task", _TASKS, ids=lambda t: t.name)
def test_matrix_benign_data(task, reg, alpha, opt):
    if _excluded(task, opt, reg):
        pytest.skip("forbidden combo (factory rejects)")
    rng = np.random.default_rng(42)
    batch = _gen(task, rng)
    lam = 0.0 if reg == RegularizationType.NONE else 1.0
    models, results = train_generalized_linear_model(
        batch, task, D,
        optimizer_type=opt,
        regularization_type=reg,
        regularization_weights=[lam],
        elastic_net_alpha=alpha,
        max_iter=60,
    )
    model = models[lam]
    for validate in _VALIDATORS[task]:
        validate(model, batch)


@pytest.mark.parametrize("task", _TASKS, ids=lambda t: t.name)
def test_matrix_outlier_data_stays_finite(task):
    """Outlier rows must not produce NaN/inf coefficients (the reference's
    'outlier' data condition is validated for stability, not accuracy)."""
    rng = np.random.default_rng(7)
    batch = _gen(task, rng, outliers=True)
    models, _ = train_generalized_linear_model(
        batch, task, D,
        regularization_type=RegularizationType.L2,
        regularization_weights=[1.0],
        max_iter=40,
    )
    coefficients_finite_validator(models[1.0], batch)
    prediction_finite_validator(models[1.0], batch)


def test_forbidden_combos_raise():
    rng = np.random.default_rng(0)
    batch = _gen(TaskType.LINEAR_REGRESSION, rng)
    with pytest.raises(ValueError):
        train_generalized_linear_model(
            batch, TaskType.LINEAR_REGRESSION, D,
            optimizer_type=OptimizerType.TRON,
            regularization_type=RegularizationType.L1,
            regularization_weights=[1.0],
        )
