"""Tier-1 gate: the whole package + bench.py are photon-lint clean.

This is what turns the PR 1-3 perf invariants from tribal knowledge into
CI: a new raw readback, jit-of-lambda, unswept spill dir or undrained
submit_io anywhere in photon_ml_tpu/ (or bench.py) fails this test
unless it is explicitly allow()-ed or baselined. The flip-side tests pin
that the enforcement is real: removing a baseline entry or a suppression
comment makes the analyzer report again."""

import json
import os
import re
import subprocess
import sys

import pytest

from photon_ml_tpu.lint import (
    Report,
    analyze_paths,
    analyze_source,
    apply_baseline,
    load_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, ".photon-lint-baseline.json")
TARGETS = ["photon_ml_tpu", "bench.py"]


@pytest.fixture()
def repo_cwd(monkeypatch):
    # baseline entries use repo-root-relative paths
    monkeypatch.chdir(REPO)


@pytest.fixture(scope="module")
def full_report():
    cwd = os.getcwd()
    os.chdir(REPO)
    try:
        return analyze_paths(TARGETS)
    finally:
        os.chdir(cwd)


def _fmt(violations):
    return "\n".join(
        f"{v.location()}: {v.rule} {v.message}" for v in violations
    )


class TestLintClean:
    def test_package_and_bench_are_clean(self, full_report):
        report = Report(
            files=full_report.files,
            violations=list(full_report.violations),
            allow_sites=full_report.allow_sites,
        )
        assert not full_report.errors, full_report.errors
        apply_baseline(report, load_baseline(BASELINE))
        assert report.violations == [], (
            "non-baselined photon-lint violations:\n"
            + _fmt(report.violations)
        )

    def test_baseline_has_no_stale_entries(self, full_report):
        report = Report(violations=list(full_report.violations))
        apply_baseline(report, load_baseline(BASELINE))
        assert report.unused_baseline == [], (
            "stale baseline entries (fixed sites?): "
            f"{report.unused_baseline}"
        )

    def test_deleting_any_baseline_entry_fails(self, full_report):
        """EVERY baseline entry is load-bearing: removing any one of
        them must resurface at least one violation."""
        entries = json.load(open(BASELINE))["entries"]
        assert entries, "baseline unexpectedly empty"
        for i in range(len(entries)):
            pruned = entries[:i] + entries[i + 1:]
            allow = {
                (e["file"], e["rule"], e["snippet"]): e.get("count", 1)
                for e in pruned
            }
            from collections import Counter

            report = Report(violations=list(full_report.violations))
            apply_baseline(report, Counter(allow))
            assert report.violations, (
                f"baseline entry {entries[i]} is not load-bearing"
            )

    def test_deleting_a_suppression_comment_fails(self, repo_cwd):
        """The in-tree allow() comments are load-bearing too: stripping
        them from the glm driver resurfaces the PL005 findings."""
        path = "photon_ml_tpu/cli/glm_driver.py"
        src = open(path).read()
        assert "# photon: allow(undrained-io)" in src
        clean = analyze_source(path, src)
        assert not [v for v in clean.violations if v.rule == "PL005"]
        stripped = re.sub(r"\s*# photon: allow\(undrained-io\)[^\n]*", "",
                          src)
        dirty = analyze_source(path, stripped)
        assert [v for v in dirty.violations if v.rule == "PL005"]

    def test_cli_end_to_end(self, repo_cwd, tmp_path):
        """The shipped CLI exits 0 against the checked-in baseline, and
        non-zero when one baseline entry is deleted — the exact command
        the acceptance criteria name."""
        r = subprocess.run(
            [sys.executable, "-m", "photon_ml_tpu.lint",
             *TARGETS, "--baseline", BASELINE],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        data = json.load(open(BASELINE))
        data["entries"] = data["entries"][1:]
        pruned = tmp_path / "pruned.json"
        pruned.write_text(json.dumps(data))
        r = subprocess.run(
            [sys.executable, "-m", "photon_ml_tpu.lint",
             *TARGETS, "--baseline", str(pruned)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r.returncode == 1, r.stdout + r.stderr

    def test_pl002_allow_sites_are_gone(self, full_report):
        """Round 10 deleted the 12 constructor-time jit(lambda) allow
        sites: the streaming objectives became pytree jit ARGUMENTS with
        shared module-level programs (ops.objective partials,
        io.streaming._tiled_fold_jit, game.streaming._chunk_jit), so the
        recompile-hazard allow-count must stay ZERO — a new allow is a
        regression, not a style choice."""
        pl002 = [
            s for s in full_report.allow_sites
            if s.rules & {"PL002", "recompile-hazard"}
        ]
        assert pl002 == [], (
            "recompile-hazard allow() sites reappeared (round 9 had 12, "
            f"round 10 removed all): {pl002}"
        )

    def test_pl001_baseline_shrank_to_one_entry(self):
        """Round 10 rewrote the host-driven optimizers to batch their
        control scalars through the counted overlap.device_get seam,
        retiring all 40 grandfathered host_lbfgs/host_tron float() pulls
        (round-9 baseline: 41 entries / 43 sites). The PL001 slice of
        the baseline must never grow back past the single remaining
        entry (round 11 added PL006 entries for the spill stream
        writers — a different rule, tested separately below)."""
        entries = [
            e for e in json.load(open(BASELINE))["entries"]
            if e["rule"] == "PL001"
        ]
        assert len(entries) == 1, entries
        assert sum(e.get("count", 1) for e in entries) == 1
        assert not any(
            "host_lbfgs" in e["file"] or "host_tron" in e["file"]
            for e in entries
        )

    def test_pl006_baseline_is_only_the_spill_stream_writers(self):
        """Round 11's reliability-hygiene rule grandfathers EXACTLY the
        spill-store stream writers (append-at-fixed-offset files behind
        the spill_write seam, progress-manifested rather than rename-
        published). Any new PL006 baseline entry is a regression: new
        artifact writes must go through the atomic helpers."""
        entries = [
            e for e in json.load(open(BASELINE))["entries"]
            if e["rule"] == "PL006"
        ]
        assert len(entries) == 3, entries
        assert {e["file"] for e in entries} == {
            "photon_ml_tpu/game/streaming.py",
            "photon_ml_tpu/io/streaming.py",
        }
        assert all("open(" in e["snippet"] for e in entries)

    def test_pl006_allow_site_is_the_atomic_helper_itself(
        self, full_report
    ):
        """The one in-tree PL006 allow() is atomic_writer's own error-
        path tmp cleanup — the helper every other site routes through.
        More allow sites mean someone is opting out of the contract."""
        pl006 = [
            s for s in full_report.allow_sites
            if s.rules & {"PL006", "reliability-hygiene"}
        ]
        assert len(pl006) == 1, pl006
        assert pl006[0].path.endswith("reliability/artifacts.py")

    def test_serving_subsystem_is_covered_and_clean(self, full_report):
        """ISSUE 7: photon_ml_tpu/serving/ + the serving driver are in
        the analyzed file set (PL001/PL002 and friends apply to the
        request path) and contribute ZERO baseline entries and ZERO
        allow() sites — the new subsystem starts at the post-round-10
        hygiene bar, not grandfathered."""
        serving_files = [
            f for f in full_report.files
            if "photon_ml_tpu/serving/" in f.replace(os.sep, "/")
        ]
        assert len(serving_files) >= 5, serving_files
        # the wire codec (ISSUE 17) is part of the request path and is
        # pinned at the same zero bar
        assert any(
            f.replace(os.sep, "/").endswith("serving/wire.py")
            for f in serving_files
        ), serving_files
        assert any(
            f.replace(os.sep, "/").endswith("cli/serving_driver.py")
            for f in full_report.files
        )
        entries = json.load(open(BASELINE))["entries"]
        assert not [
            e for e in entries
            if "serving" in e["file"]
        ], "serving code must not be baselined"
        assert not [
            s for s in full_report.allow_sites
            if "serving" in s.path.replace(os.sep, "/")
        ], "serving code must not carry allow() suppressions"

    def test_pod_sharding_modules_covered_and_clean(self, full_report):
        """ISSUE 9: the pod-scale sharding modules (game/pod.py and the
        extended residual router) are in the analyzed set and contribute
        ZERO baseline entries and ZERO allow() sites — the routed hot
        path's no-hidden-host-sync discipline is structural, not
        grandfathered."""
        files = [f.replace(os.sep, "/") for f in full_report.files]
        assert any(f.endswith("game/pod.py") for f in files)
        assert any(f.endswith("game/residual_routing.py") for f in files)
        entries = json.load(open(BASELINE))["entries"]
        for mod in ("game/pod.py", "game/residual_routing.py"):
            assert not [
                e for e in entries if e["file"].replace(os.sep, "/").endswith(mod)
            ], f"{mod} must not be baselined"
            assert not [
                s for s in full_report.allow_sites
                if s.path.replace(os.sep, "/").endswith(mod)
            ], f"{mod} must not carry allow() suppressions"

    def test_registry_subsystem_covered_and_clean(self, full_report):
        """ISSUE 10: photon_ml_tpu/registry/ (model registry, stats
        cache, warm-start alignment, gates, watcher) is in the analyzed
        set and contributes ZERO baseline entries and ZERO allow()
        sites — in particular every artifact write in the publish
        protocol goes through the atomic helpers (PL006) with no
        except-and-pass, structurally."""
        registry_files = [
            f for f in full_report.files
            if "photon_ml_tpu/registry/" in f.replace(os.sep, "/")
        ]
        assert len(registry_files) >= 5, registry_files
        entries = json.load(open(BASELINE))["entries"]
        assert not [
            e for e in entries if "registry" in e["file"]
        ], "registry code must not be baselined"
        assert not [
            s for s in full_report.allow_sites
            if "photon_ml_tpu/registry/" in s.path.replace(os.sep, "/")
        ], "registry code must not carry allow() suppressions"

    def test_pl007_lands_at_zero(self, full_report):
        """ISSUE 8: the request-path-hygiene rule (no untimed
        Condition.wait / Future.result in serving/) ships with a ZERO
        baseline and zero allow() sites — every wait the request path
        performs is bounded from day one, and any new unbounded wait is
        a lint failure, not a grandfathered hang."""
        from photon_ml_tpu.lint.core import RULES, _load_rules

        _load_rules()
        assert "PL007" in RULES, sorted(RULES)
        entries = [
            e for e in json.load(open(BASELINE))["entries"]
            if e["rule"] == "PL007"
        ]
        assert entries == [], entries
        pl007_allows = [
            s for s in full_report.allow_sites
            if s.rules & {"PL007", "request-path-hygiene"}
        ]
        assert pl007_allows == [], pl007_allows
        # the rule applies to the live request path: frontend + batcher
        # + programs are all in the analyzed set
        serving = [
            f for f in full_report.files
            if "photon_ml_tpu/serving/" in f.replace(os.sep, "/")
        ]
        assert any(f.endswith("frontend.py") for f in serving), serving
        assert any(f.endswith("admission.py") for f in serving), serving

    def test_concurrency_rules_land_at_zero(self, full_report):
        """ISSUE 11: PL008-PL010 ship with ZERO baseline entries
        package-wide and ZERO allow() sites in `serving/` and
        `registry/` — the thread plane's guard discipline is
        structural from day one. PL009 additionally can never GAIN a
        baseline entry (write/load both refuse), so the pin here is
        belt-and-braces."""
        from photon_ml_tpu.lint import all_rules

        rules = all_rules()
        for rid in ("PL008", "PL009", "PL010"):
            assert rid in rules, sorted(rules)
        entries = [
            e for e in json.load(open(BASELINE))["entries"]
            if e["rule"] in ("PL008", "PL009", "PL010")
        ]
        assert entries == [], entries
        slugs = {
            "PL008", "unguarded-shared-state",
            "PL009", "lock-order-inversion",
            "PL010", "atomicity-hygiene",
        }
        allows = [
            s for s in full_report.allow_sites if s.rules & slugs
        ]
        assert allows == [], allows
        for subsystem in ("photon_ml_tpu/serving/",
                          "photon_ml_tpu/registry/"):
            assert not [
                s for s in full_report.allow_sites
                if subsystem in s.path.replace(os.sep, "/")
            ], f"{subsystem} must not carry allow() suppressions"

    def test_concurrency_pass_is_enforced_not_decorative(self):
        """Stripping ONE guard from the real watcher resurfaces PL008:
        the zero-violation state above is load-bearing analysis, not a
        rule that never fires on real code."""
        path = "photon_ml_tpu/registry/watcher.py"
        src = open(path).read()
        clean = analyze_source(path, src)
        assert not [
            v for v in clean.violations if v.rule == "PL008"
        ], _fmt(clean.violations)
        stripped = src.replace(
            "        with self._lock:\n"
            "            if not self._watching_swap:\n"
            "                return",
            "        if not self._watching_swap:\n"
            "            return",
        )
        assert stripped != src, "watcher guard shape changed; update me"
        dirty = analyze_source(path, stripped)
        assert [v for v in dirty.violations if v.rule == "PL008"]

    def test_obs_subsystem_covered_clean_and_host_only(self, full_report):
        """ISSUE 13: photon_ml_tpu/obs/ (trace, registry, flight
        recorder, folded events) is in the analyzed set at the
        zero-baseline bar — ZERO baseline entries and ZERO allow()
        sites — and is structurally host-arithmetic-only: no obs module
        imports jax in any form, so no obs code can ever touch a jax
        value (the PL001 concern made impossible rather than merely
        clean). Telemetry must never add a device sync, a lowering, or
        a readback."""
        obs_files = [
            f for f in full_report.files
            if "photon_ml_tpu/obs/" in f.replace(os.sep, "/")
        ]
        # ISSUE 15 adds fleet.py (collector/stitching) + slo.py
        # (burn-rate engine) to the set — both at the same bar
        assert len(obs_files) >= 7, obs_files
        names = {os.path.basename(f) for f in obs_files}
        assert {"fleet.py", "slo.py"} <= names, names
        entries = json.load(open(BASELINE))["entries"]
        assert not [
            e for e in entries
            if "photon_ml_tpu/obs/" in e["file"].replace(os.sep, "/")
        ], "obs code must not be baselined"
        assert not [
            s for s in full_report.allow_sites
            if "photon_ml_tpu/obs/" in s.path.replace(os.sep, "/")
        ], "obs code must not carry allow() suppressions"
        jax_import = re.compile(r"^\s*(import\s+jax|from\s+jax)", re.M)
        for f in obs_files:
            src = open(os.path.join(REPO, f)).read()
            assert not jax_import.search(src), (
                f"{f}: obs code imports jax — telemetry is host "
                "arithmetic only"
            )

    def test_spmd_rules_land_at_zero(self, full_report):
        """ISSUE 14: PL011-PL014 ship with ZERO baseline entries
        package-wide and ZERO allow() sites anywhere — the SPMD
        discipline (axis constants, sharding contracts, shard-local
        bank access, donation hygiene) is structural from day one.
        PL012 additionally can never GAIN a baseline entry (write/load
        both refuse), so the pin here is belt-and-braces."""
        from photon_ml_tpu.lint import all_rules

        rules = all_rules()
        for rid in ("PL011", "PL012", "PL013", "PL014"):
            assert rid in rules, sorted(rules)
        entries = [
            e for e in json.load(open(BASELINE))["entries"]
            if e["rule"] in ("PL011", "PL012", "PL013", "PL014")
        ]
        assert entries == [], entries
        slugs = {
            "PL011", "mesh-axis-discipline",
            "PL012", "sharded-bank-host-gather",
            "PL013", "reduction-completeness",
            "PL014", "donation-hygiene",
        }
        allows = [
            s for s in full_report.allow_sites if s.rules & slugs
        ]
        assert allows == [], allows

    def test_spmd_subsystems_carry_no_allow_sites(self, full_report):
        """The acceptance bar: serving/, game/, parallel/, registry/
        and obs/ carry NO allow() suppressions of any rule — the five
        subsystems the sharding contracts cover hold the zero bar
        wholesale."""
        for subsystem in ("photon_ml_tpu/serving/",
                          "photon_ml_tpu/game/",
                          "photon_ml_tpu/parallel/",
                          "photon_ml_tpu/registry/",
                          "photon_ml_tpu/obs/"):
            assert not [
                s for s in full_report.allow_sites
                if subsystem in s.path.replace(os.sep, "/")
            ], f"{subsystem} must not carry allow() suppressions"

    def test_sharding_inventory_is_complete(self, full_report):
        """Every jit/shard_map mesh entry point in the package is
        present in the contract inventory with a declaration, and the
        committed SHARDING.md matches a fresh render (the CI drift
        gate's in-process twin)."""
        from photon_ml_tpu.lint import sharding_contracts as sc

        assert full_report.package is not None
        rows = sc.inventory(full_report.package)
        # the count is asserted exactly: a NEW jit/shard_map entry
        # point must land here (with a declaration) or fail PL011.
        # ISSUE 20 shrank the inventory 38 -> 36: five legacy
        # distributed fit builders collapsed into feature_sharded_glm_fit
        # wrappers and the problem.py hdiag variants merged, while the
        # unified-mesh grid programs (game/unified.py) added six
        # declared entries
        assert len(rows) == 36, [
            (r["module"], r["entry"]) for r in rows
        ]
        assert all(r["declared"] == "yes" for r in rows), [
            r for r in rows if r["declared"] != "yes"
        ]
        modules = {r["module"] for r in rows}
        for expected in (
            "photon_ml_tpu/game/pod.py",
            "photon_ml_tpu/game/residual_routing.py",
            "photon_ml_tpu/game/random_effect.py",
            "photon_ml_tpu/game/unified.py",
            "photon_ml_tpu/optim/problem.py",
            "photon_ml_tpu/parallel/distributed.py",
            "photon_ml_tpu/parallel/shuffle.py",
            "photon_ml_tpu/ops/tiled_sparse.py",
            "photon_ml_tpu/serving/programs.py",
            "photon_ml_tpu/serving/swap.py",
        ):
            assert expected in modules, sorted(modules)
        scopes = sc.export_scopes(full_report.package)
        assert len(scopes) == 6, scopes
        drift = sc.check_sharding_md(
            os.path.join(REPO, "SHARDING.md"), full_report.package
        )
        assert drift is None, drift

    def test_stripping_a_sharding_declaration_resurfaces_pl011(self):
        """The contract layer is enforced, not decorative: removing one
        real declaration from the pod update program resurfaces the
        missing-declaration violation."""
        path = "photon_ml_tpu/game/pod.py"
        src = open(path).read()
        decl = ("    # photon: sharding(axes=[entity], in=?, "
                "out=[entity,r,r,r], donates=[0])\n")
        assert decl in src, "pod declaration shape changed; update me"
        clean = analyze_source(path, src)
        assert not [v for v in clean.violations if v.rule == "PL011"], \
            _fmt(clean.violations)
        dirty = analyze_source(path, src.replace(decl, ""))
        assert [
            v for v in dirty.violations
            if v.rule == "PL011" and "no '# photon: sharding" in v.message
        ]

    def test_stripping_an_export_declaration_resurfaces_pl012(self):
        """The export scopes are audited declarations: removing the one
        on the pod model's bank property makes its to_global() a PL012
        violation again."""
        path = "photon_ml_tpu/game/pod.py"
        src = open(path).read()
        clean = analyze_source(path, src)
        assert not [v for v in clean.violations if v.rule == "PL012"], \
            _fmt(clean.violations)
        stripped = src.replace(
            "    @property\n"
            "    # photon: sharding(export)\n"
            "    def bank(self) -> Array:",
            "    @property\n"
            "    def bank(self) -> Array:",
        )
        assert stripped != src, "pod bank property changed; update me"
        dirty = analyze_source(path, stripped)
        assert [v for v in dirty.violations if v.rule == "PL012"]

    def test_reverting_tiled_sparse_axis_constants_resurfaces_pl011(self):
        """Round 19's real PL011 findings: the tiled batch builders
        bound their axis parameters to string literals. Reverting the
        constant references fails the literal rule again."""
        path = "photon_ml_tpu/ops/tiled_sparse.py"
        src = open(path).read()
        assert 'data_axis: str = DATA_AXIS' in src
        clean = analyze_source(path, src)
        assert not [v for v in clean.violations if v.rule == "PL011"], \
            _fmt(clean.violations)
        reverted = src.replace(
            "    data_axis: str = DATA_AXIS,\n"
            "    model_axis: str = MODEL_AXIS,",
            '    data_axis: str = "data",\n'
            '    model_axis: str = "model",',
        )
        assert reverted != src
        dirty = analyze_source(path, reverted)
        lits = [
            v for v in dirty.violations
            if v.rule == "PL011" and "literal" in v.message
        ]
        assert len(lits) == 2, _fmt(dirty.violations)

    def test_interleave_harness_is_analyzed(self, full_report):
        """The testing/ package (interleaving harness) is part of the
        analyzed set and holds the same bar — its own thread-shared
        flags carry guarded-by declarations, not suppressions."""
        files = [f.replace(os.sep, "/") for f in full_report.files]
        assert any(
            f.endswith("testing/interleave.py") for f in files
        ), files

    def test_determinism_rules_land_at_zero(self, full_report):
        """ISSUE 19: PL015-PL018 ship with ZERO baseline entries
        package-wide and ZERO allow() sites anywhere — artifact-order
        and entropy discipline is structural, expressed through fixes
        and '# photon: entropy(<reason>)' declarations, never through
        suppressions. PL016/PL018 additionally can never GAIN a
        baseline entry (write/load both refuse)."""
        from photon_ml_tpu.lint import all_rules

        rules = all_rules()
        for rid in ("PL015", "PL016", "PL017", "PL018"):
            assert rid in rules, sorted(rules)
        entries = [
            e for e in json.load(open(BASELINE))["entries"]
            if e["rule"] in ("PL015", "PL016", "PL017", "PL018")
        ]
        assert entries == [], entries
        slugs = {
            "PL015", "unordered-iteration-to-artifact",
            "PL016", "ambient-entropy-in-artifact",
            "PL017", "float-accumulation-order",
            "PL018", "wire-contract-completeness",
        }
        allows = [
            s for s in full_report.allow_sites if s.rules & slugs
        ]
        assert allows == [], allows

    def test_stripping_an_entropy_declaration_resurfaces_pl016(self):
        """The declaration grammar is enforced, not decorative:
        removing the span-epoch declaration from the tracer makes its
        epoch exports PL016 violations again."""
        path = "photon_ml_tpu/obs/trace.py"
        src = open(path).read()
        decl = ("  # photon: entropy(per-boot span-epoch anchor; "
                "the wall/perf pair IS the timeline contract)")
        assert decl in src, "trace epoch declaration changed; update me"
        clean = analyze_source(path, src)
        assert not [v for v in clean.violations if v.rule == "PL016"], \
            _fmt(clean.violations)
        dirty = analyze_source(path, src.replace(decl, ""))
        assert [
            v for v in dirty.violations
            if v.rule == "PL016" and "time.time()" in v.message
        ], _fmt(dirty.violations)

    def test_reverting_retry_jitter_seed_resurfaces_pl016(self):
        """Regression pin for the real defect PL016 caught on its first
        package run: the backoff jitter was seeded from builtin
        hash((seam, attempt)) — PYTHONHASHSEED-randomized, so the
        'deterministic' retry schedule differed per process. Reverting
        the crc32 fix resurfaces the finding."""
        path = "photon_ml_tpu/reliability/retry.py"
        src = open(path).read()
        fixed = 'seed = zlib.crc32(f"{seam}:{attempt}".encode("utf-8"))'
        assert fixed in src, "retry jitter seed changed; update me"
        clean = analyze_source(path, src)
        assert not [v for v in clean.violations if v.rule == "PL016"], \
            _fmt(clean.violations)
        dirty = analyze_source(
            path, src.replace(fixed, "seed = hash((seam, attempt))")
        )
        assert [
            v for v in dirty.violations
            if v.rule == "PL016" and "seeds Random" in v.message
        ], _fmt(dirty.violations)

    def test_reverting_bench_flood_seed_resurfaces_pl016(self):
        """Same pin for the flood-payload generator: hash(key)-seeded
        default_rng meant parent and relaunched child processes built
        DIFFERENT payloads for the same key, drifting cache-hit
        accounting."""
        path = "bench.py"
        src = open(path).read()
        fixed = ("seed = zlib.crc32(\n"
                 '                f"{key[0]}:{key[1]}:{key[2]}"'
                 '.encode("utf-8")\n'
                 "            )")
        assert fixed in src, "bench flood seed changed; update me"
        # no clean-half re-analysis of bench.py here (it is the largest
        # file in the run): test_determinism_rules_land_at_zero already
        # proves the fixed tree carries zero PL016
        dirty = analyze_source(
            path, src.replace(fixed, "seed = hash(key)")
        )
        assert [
            v for v in dirty.violations
            if v.rule == "PL016" and "default_rng" in v.message
        ], _fmt(dirty.violations)

    def test_unsorting_the_signature_walk_resurfaces_pl015(self):
        """The PL015 pin on the lineage-critical artifact: the registry
        content signature digests a sorted os.walk. Dropping the sort
        makes the digest OS-iteration-order dependent — the same tree
        would sign differently across hosts — and the analyzer flags
        the walk again."""
        path = "photon_ml_tpu/registry/registry.py"
        src = open(path).read()
        fixed = "for root, dirs, files in sorted(os.walk(model_dir)):"
        assert fixed in src, "signature walk changed; update me"
        clean = analyze_source(path, src)
        assert not [v for v in clean.violations if v.rule == "PL015"], \
            _fmt(clean.violations)
        dirty = analyze_source(
            path,
            src.replace(
                fixed, "for root, dirs, files in os.walk(model_dir):"
            ),
        )
        assert [
            v for v in dirty.violations
            if v.rule == "PL015" and "os.walk" in v.message
        ], _fmt(dirty.violations)

    def test_reverting_native_index_partition_sort_resurfaces_pl015(self):
        """Round 22's real PL015 finding: the partitioned index builder
        iterated ``set(keys)`` straight into the per-partition stores,
        so the same key set produced byte-different index files per
        process. Reverting the sort resurfaces the finding."""
        path = "photon_ml_tpu/utils/native_index.py"
        src = open(path).read()
        fixed = ("    for key in sorted(set(keys)):\n"
                 "        parts[zlib.crc32")
        assert fixed in src, "partition loop changed; update me"
        clean = analyze_source(path, src)
        assert not [v for v in clean.violations if v.rule == "PL015"], \
            _fmt(clean.violations)
        dirty = analyze_source(
            path,
            src.replace(
                fixed,
                "    for key in set(keys):\n        parts[zlib.crc32",
            ),
        )
        assert [
            v for v in dirty.violations
            if v.rule == "PL015" and "set(...)" in v.message
        ], _fmt(dirty.violations)

    def test_stripping_routing_allowlist_resurfaces_pl018(self, tmp_path):
        """The transport fix PL018 forced: without the response-type
        allowlist in _read_frames, routing.py references NO response
        MSG_* constants — the dispatch leg flags all three response
        types (and the original protocol-confusion hole returns)."""
        import shutil

        serving = os.path.join(REPO, "photon_ml_tpu", "serving")
        pkg_dir = tmp_path / "serving"
        pkg_dir.mkdir()
        for name in ("wire.py", "frontend.py", "routing.py"):
            shutil.copy(os.path.join(serving, name), pkg_dir / name)
        clean = analyze_paths([str(pkg_dir)])
        assert not [v for v in clean.violations if v.rule == "PL018"], \
            _fmt(clean.violations)
        routing_src = (pkg_dir / "routing.py").read_text()
        allowlist = (
            "                if mtype not in (\n"
            "                    wirefmt.MSG_JSON,\n"
            "                    wirefmt.MSG_SCORE_RESPONSE,\n"
            "                    wirefmt.MSG_PARTIAL_RESPONSE,\n"
            "                    wirefmt.MSG_TRACE_RESPONSE,\n"
            "                ):\n"
            "                    self.unmatched_responses += 1\n"
            "                    continue\n"
        )
        assert allowlist in routing_src, "routing allowlist changed"
        (pkg_dir / "routing.py").write_text(
            routing_src.replace(allowlist, "")
        )
        dirty = analyze_paths([str(pkg_dir)])
        undispatched = {
            v.message.split(" ", 1)[0]
            for v in dirty.violations
            if v.rule == "PL018" and "never dispatched" in v.message
        }
        assert undispatched == {
            "MSG_SCORE_RESPONSE", "MSG_PARTIAL_RESPONSE",
            "MSG_TRACE_RESPONSE",
        }, _fmt(dirty.violations)

    def test_determinism_harness_is_analyzed(self, full_report):
        """The twin-run harness and its artifact targets are part of
        the analyzed set and hold the zero bar themselves — the gate
        that checks determinism is checked for determinism."""
        files = [f.replace(os.sep, "/") for f in full_report.files]
        for mod in ("testing/determinism.py",
                    "testing/determinism_targets.py"):
            assert any(f.endswith(mod) for f in files), (mod, files)

    def test_json_lists_allow_sites_with_seam_accounting(self, repo_cwd):
        r = subprocess.run(
            [sys.executable, "-m", "photon_ml_tpu.lint",
             *TARGETS, "--baseline", BASELINE, "--json"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        data = json.loads(r.stdout)
        assert data["violations"] == []
        assert data["baselined"] > 0
        sites = data["allow_sites"]
        assert sites, "expected in-tree allow() sites"
        # every hidden-host-sync allow in package code is seam-accounted
        for s in sites:
            if set(s["rules"]) & {"PL001", "hidden-host-sync"}:
                if s["file"].startswith("photon_ml_tpu/"):
                    assert s["seam_ok"] is True, s
