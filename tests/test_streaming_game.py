"""Out-of-core GAME training (game/streaming.py): streamed coordinate
descent over spilled chunks under a host-memory budget.

Parity philosophy: the streamed CD runs the SAME math as the in-memory
CD (same index spaces — both maps sort keys; same entity codes; same
bucket contents; same residual algebra) but accumulates objective
partials chunk-by-chunk and drives the FE solve host-side. fp32
reordering noise (~1e-7/evaluation) is amplified through optimizer
iterates, so coefficient agreement lands at ~1e-4 relative after a full
CD run (the TRON fixed effect is the tightest pairing — its host driver
walks the in-jit iterate sequence step for step); the OBJECTIVE agrees
much tighter. PERF_NOTES round 7 records the measured envelopes.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu.game.config import (
    FeatureShardConfiguration,
    FixedEffectDataConfiguration,
    ProjectorType,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.evaluation import EvaluatorType
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.optim.config import GLMOptimizationConfiguration
from photon_ml_tpu.task import TaskType


def _write_game_files(base, rng, *, n_files=3, rows_per_file=80, n_users=6,
                      d_g=5, d_u=3):
    from conftest import game_example_schema

    os.makedirs(base, exist_ok=True)
    w_g = np.linspace(-1, 1, d_g)
    w_u = np.random.default_rng(7).normal(size=(n_users, d_u))
    for fi in range(n_files):
        recs = []
        for i in range(rows_per_file):
            u = int(rng.integers(0, n_users))
            xg = rng.normal(size=d_g)
            xu = rng.normal(size=d_u)
            z = float(xg @ w_g + xu @ w_u[u])
            recs.append({
                "uid": f"f{fi}-{i}",
                "response": float(1 / (1 + np.exp(-z)) > rng.uniform()),
                "metadataMap": {"userId": f"user{u}"},
                "features": [
                    {"name": f"g{j}", "term": "", "value": float(xg[j])}
                    for j in range(d_g)
                ],
                "userFeatures": [
                    {"name": f"u{j}", "term": "", "value": float(xu[j])}
                    for j in range(d_u)
                ],
            })
        write_container(
            os.path.join(base, f"part-{fi}.avro"),
            game_example_schema(), recs,
        )


SHARDS = [
    FeatureShardConfiguration("globalShard", ["features"]),
    FeatureShardConfiguration("userShard", ["userFeatures"]),
]
FE_DATA = {"global": FixedEffectDataConfiguration("globalShard")}
RE_DATA = {
    "per-user": RandomEffectDataConfiguration(
        "userId", "userShard", projector_type=ProjectorType.IDENTITY
    )
}


def _combo(fe_spec, re_spec):
    return {
        "global": GLMOptimizationConfiguration.parse(fe_spec),
        "per-user": GLMOptimizationConfiguration.parse(re_spec),
    }


def _in_memory_cd(train_dir, combo, num_iterations):
    from photon_ml_tpu.game.coordinate import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.game.coordinate_descent import CoordinateDescent
    from photon_ml_tpu.game.data import build_game_dataset_from_files
    from photon_ml_tpu.game.random_effect import (
        RandomEffectOptimizationProblem,
    )
    from photon_ml_tpu.game.random_effect_data import (
        build_random_effect_dataset,
    )
    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.optim.problem import create_glm_problem

    task = TaskType.LOGISTIC_REGRESSION
    ds = build_game_dataset_from_files([train_dir], SHARDS, ["userId"])
    red = build_random_effect_dataset(ds, RE_DATA["per-user"])
    coords = {
        "global": FixedEffectCoordinate(
            name="global", dataset=ds,
            problem=create_glm_problem(
                task, ds.shards["globalShard"].dim,
                config=combo["global"].optimizer_config,
                regularization=combo["global"].regularization,
                intercept_index=ds.shards["globalShard"].intercept_index,
            ),
            feature_shard_id="globalShard",
            reg_weight=combo["global"].reg_weight,
        ),
        "per-user": RandomEffectCoordinate(
            name="per-user", dataset=ds, re_dataset=red,
            problem=RandomEffectOptimizationProblem(
                loss_for_task(task),
                combo["per-user"].optimizer_config,
                combo["per-user"].regularization,
                reg_weight=combo["per-user"].reg_weight,
            ),
        ),
    }
    return CoordinateDescent(coords, ds, task).run(num_iterations), ds, red


class TestStreamingGameParity:
    def test_matches_in_memory_cd(self, tmp_path, rng):
        """Streamed GAME CD over >= 3 chunks == in-memory CD: same data,
        same RNG, same index/entity spaces. TRON fixed effect (host
        driver == in-jit iterate sequence), LBFGS random effects (the
        SAME fused bucket solvers run on identical bucket contents)."""
        from photon_ml_tpu.game.streaming import train_streaming_game

        train = str(tmp_path / "train")
        _write_game_files(train, rng)
        combo = _combo("50,1e-6,0.5,1,TRON,L2", "50,1e-6,1.0,1,LBFGS,L2")
        ref, _, _ = _in_memory_cd(train, combo, 2)
        res, extras = train_streaming_game(
            [train], SHARDS, FE_DATA, RE_DATA, combo,
            TaskType.LOGISTIC_REGRESSION, num_iterations=2,
            memory_budget_bytes=100 * 80,  # tiny -> many chunks
        )
        assert extras["store"].count >= 3
        # objective parity is tight (sum reordering only)
        np.testing.assert_allclose(
            res.objective_history, ref.objective_history, rtol=1e-4
        )
        ref_fe = np.asarray(ref.model.get_model("global").model.means)
        st_fe = np.asarray(res.game_model.get_model("global").model.means)
        np.testing.assert_allclose(st_fe, ref_fe, rtol=2e-3, atol=3e-4)
        ref_bank = np.asarray(ref.model.get_model("per-user").bank)
        st_bank = np.asarray(res.game_model.get_model("per-user").bank)
        np.testing.assert_allclose(st_bank, ref_bank, rtol=2e-3, atol=3e-4)

    def test_single_chunk_single_iteration_is_tight(self, tmp_path, rng):
        """With one CD iteration the only drift is inside the solves:
        the TRON FE and the bucket RE land at ~1e-5 of the in-memory
        fit (the coefficient-parity envelope before CD-level residual
        coupling compounds it)."""
        from photon_ml_tpu.game.streaming import train_streaming_game

        train = str(tmp_path / "train")
        _write_game_files(train, rng)
        combo = _combo("50,1e-6,0.5,1,TRON,L2", "50,1e-6,1.0,1,LBFGS,L2")
        ref, _, _ = _in_memory_cd(train, combo, 1)
        res, extras = train_streaming_game(
            [train], SHARDS, FE_DATA, RE_DATA, combo,
            TaskType.LOGISTIC_REGRESSION, num_iterations=1,
            memory_budget_bytes=100 * 80,
        )
        assert extras["store"].count >= 3
        ref_fe = np.asarray(ref.model.get_model("global").model.means)
        st_fe = np.asarray(res.game_model.get_model("global").model.means)
        scale = np.abs(ref_fe).max()
        assert np.abs(st_fe - ref_fe).max() <= 2e-4 * scale

    def test_bucket_structure_matches_in_memory(self, tmp_path, rng):
        """The spilled grouping reproduces the in-memory buckets: same
        entity->capacity classes, same per-entity sample sets in the
        same (ascending global row) order."""
        from photon_ml_tpu.game.random_effect_data import (
            build_random_effect_dataset,
        )
        from photon_ml_tpu.game.data import build_game_dataset_from_files
        from photon_ml_tpu.game.streaming import (
            SpilledREBuckets,
            scan_game_stream,
            stage_game_stream,
        )

        train = str(tmp_path / "train")
        _write_game_files(train, rng)
        imaps, eidx, stats = scan_game_stream(
            [train], SHARDS, ["userId"]
        )
        store, _ = stage_game_stream(
            [train], SHARDS, ["userId"], imaps, eidx, stats,
            rows_per_chunk=64,
        )
        spilled = SpilledREBuckets(
            store, "userId", "userShard", stats.entity_counts["userId"],
        )
        ds = build_game_dataset_from_files([train], SHARDS, ["userId"])
        red = build_random_effect_dataset(ds, RE_DATA["per-user"])
        mem = {}
        for b in red.buckets:
            for e_i, code in enumerate(b.entity_codes):
                rows = b.row_index[e_i]
                mem[int(code)] = (
                    b.capacity, rows[rows >= 0].tolist()
                )
        st = {}
        for codes, arrs in spilled.iter_segments():
            for e_i, code in enumerate(codes):
                rows = arrs["rows"][e_i]
                st[int(code)] = (
                    arrs["rows"].shape[1], rows[rows >= 0].tolist()
                )
        assert st == mem

    def test_streamed_validation_matches_in_memory_auc(self, tmp_path, rng):
        """Streamed GAME validation (histogram AUC over chunks) lands
        within 1e-3 of the exact sort-based AUC on the same scores."""
        from photon_ml_tpu.evaluation import (
            Evaluator,
        )
        from photon_ml_tpu.game.streaming import train_streaming_game

        import jax.numpy as jnp

        train = str(tmp_path / "train")
        val = str(tmp_path / "val")
        _write_game_files(train, rng)
        _write_game_files(val, rng, n_files=2, rows_per_file=150)
        combo = _combo("40,1e-6,0.5,1,TRON,L2", "40,1e-6,1.0,1,LBFGS,L2")
        res, extras = train_streaming_game(
            [train], SHARDS, FE_DATA, RE_DATA, combo,
            TaskType.LOGISTIC_REGRESSION, num_iterations=1,
            memory_budget_bytes=100 * 80, validate_paths=[val],
            evaluator_types=[EvaluatorType.parse("AUC")],
        )
        streamed_auc = res.validation_history[-1]["AUC"]
        # exact reference: rebuild total scores chunk-wise from the
        # exported model banks over the staged validation chunks
        vstore = extras["validate_store"]
        zs, labs, wgts = [], [], []
        fe = res.game_model.get_model("global")
        re_m = res.game_model.get_model("per-user")
        for i in range(vstore.count):
            c = vstore.chunk(i)
            w = np.asarray(fe.model.means)
            z = (c["v__globalShard"] * w[c["ix__globalShard"]]).sum(axis=1)
            codes = c["code__userId"]
            valid = (codes >= 0) & (c["wgt"] > 0)
            bank = np.asarray(re_m.bank)
            rows = bank[np.maximum(codes, 0)]
            z_u = np.take_along_axis(
                rows, c["ix__userShard"], axis=1
            )
            z = z + np.where(valid, (c["v__userShard"] * z_u).sum(axis=1), 0)
            zs.append(z + c["off"])
            labs.append(c["lab"])
            wgts.append(c["wgt"])
        z = np.concatenate(zs)
        exact = float(Evaluator(EvaluatorType.parse("AUC")).evaluate(
            jnp.asarray(z), jnp.asarray(np.concatenate(labs)),
            jnp.asarray(np.concatenate(wgts)),
        ))
        assert abs(streamed_auc - exact) < 1e-3


class TestStreamingGameGates:
    def _params(self, tmp_path, **kw):
        from photon_ml_tpu.cli.game_training_driver import GameTrainingParams

        base = dict(
            train_input_dirs=[str(tmp_path / "train")],
            output_dir=str(tmp_path / "out"),
            task_type=TaskType.LOGISTIC_REGRESSION,
            feature_shards=SHARDS,
            fixed_effect_data_configs=dict(FE_DATA),
            fixed_effect_opt_configs={"global": "20,1e-6,0.1,1,LBFGS,L2"},
            random_effect_data_configs=dict(RE_DATA),
            random_effect_opt_configs={"per-user": "20,1e-6,1.0,1,LBFGS,L2"},
            streaming=True,
        )
        base.update(kw)
        return GameTrainingParams(**base)

    def test_rejects_non_identity_projector(self, tmp_path):
        p = self._params(
            tmp_path,
            random_effect_data_configs={
                "per-user": RandomEffectDataConfiguration(
                    "userId", "userShard",
                    projector_type=ProjectorType.INDEX_MAP,
                )
            },
        )
        with pytest.raises(ValueError, match="IDENTITY projector"):
            p.validate()

    def test_rejects_active_data_cap(self, tmp_path):
        p = self._params(
            tmp_path,
            random_effect_data_configs={
                "per-user": RandomEffectDataConfiguration(
                    "userId", "userShard",
                    active_data_upper_bound=4,
                    projector_type=ProjectorType.IDENTITY,
                )
            },
        )
        with pytest.raises(ValueError, match="active-data-upper-bound"):
            p.validate()

    def test_streaming_checkpoint_supported_sharded_evaluator_not(
        self, tmp_path
    ):
        # round 11 (reliability layer): streaming + --checkpoint-dir is
        # now a SUPPORTED combination (staged-store manifests + per-
        # iteration CD snapshots), so validate() must accept it
        p = self._params(tmp_path, checkpoint_dir=str(tmp_path / "ckpt"))
        p.validate()
        p = self._params(
            tmp_path, evaluator_types=[EvaluatorType.parse("AUC:userId")]
        )
        with pytest.raises(ValueError, match="sharded evaluator"):
            p.validate()

    def test_rejects_budget_without_streaming_glm(self, tmp_path):
        from photon_ml_tpu.cli.glm_driver import GLMParams

        p = GLMParams(
            train_dir="x", output_dir="y", stream_memory_budget=1 << 20
        )
        with pytest.raises(ValueError, match="stream-memory-budget"):
            p.validate()


@pytest.mark.slow
class TestStreamingGameDriver:
    def test_driver_end_to_end(self, tmp_path, rng):
        """Streamed driver: trains over >= 3 chunks, streams validation,
        writes the standard best-model layout (round-trips through
        load_game_model) and reports the budget + RSS high-water in
        metrics.json."""
        from photon_ml_tpu.cli.game_training_driver import (
            GameTrainingDriver,
            GameTrainingParams,
        )
        from photon_ml_tpu.game.model_io import load_game_model

        train = str(tmp_path / "train")
        val = str(tmp_path / "val")
        _write_game_files(train, rng)
        _write_game_files(val, rng, n_files=2, rows_per_file=150)
        params = GameTrainingParams(
            train_input_dirs=[train],
            validate_input_dirs=[val],
            output_dir=str(tmp_path / "out"),
            task_type=TaskType.LOGISTIC_REGRESSION,
            feature_shards=SHARDS,
            fixed_effect_data_configs=dict(FE_DATA),
            fixed_effect_opt_configs={"global": "50,1e-6,0.5,1,TRON,L2"},
            random_effect_data_configs=dict(RE_DATA),
            random_effect_opt_configs={"per-user": "50,1e-6,1.0,1,LBFGS,L2"},
            num_iterations=2,
            evaluator_types=[EvaluatorType.parse("AUC")],
            streaming=True,
            stream_memory_budget=100 * 80,
        )
        GameTrainingDriver(params).run()
        out = params.output_dir
        m = json.load(open(os.path.join(out, "metrics.json")))
        assert len(m["objective_history"]) == 2
        assert m["objective_history"][-1] <= m["objective_history"][0]
        assert m["validation_history"][-1]["AUC"] > 0.6
        assert m["streaming"]["num_chunks"] >= 3
        assert m["streaming"]["peak_rss_bytes"] > 0
        assert m["streaming"]["diagnostics"]["reservoir_rows"] > 0
        loaded = load_game_model(os.path.join(out, "best-model"))
        assert set(loaded.coordinate_names()) == {"global", "per-user"}


@pytest.mark.slow
class TestStreamingGameBoundedMemory:
    def test_peak_rss_bounded_by_budget(self, tmp_path):
        """Train a multi-chunk GAME set under a tiny
        --stream-memory-budget and assert the process high-water stays
        under budget + fixed slack (the python/jax baseline + models),
        NOT under the dataset size: the record form of the stream is
        hundreds of MB; the budget is 2 MB."""
        script = r"""
import os, resource, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(sys.argv[0]) or ".")
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.io import schemas
from photon_ml_tpu.game.config import (FeatureShardConfiguration,
    FixedEffectDataConfiguration, RandomEffectDataConfiguration,
    ProjectorType)
from photon_ml_tpu.optim.config import GLMOptimizationConfiguration
from photon_ml_tpu.task import TaskType

tmp = sys.argv[1]
schema = {
    "name": "GameExample", "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "response", "type": "double"},
        {"name": "metadataMap",
         "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
        {"name": "features",
         "type": {"type": "array", "items": schemas.FEATURE_AVRO}},
        {"name": "userFeatures",
         "type": {"type": "array", "items": "FeatureAvro"}},
    ],
}
rng = np.random.default_rng(0)
n_files, rows, d_g, d_u, n_users = 4, 12_000, 24, 8, 400
for fi in range(n_files):
    recs = []
    for i in range(rows):
        u = int(rng.integers(0, n_users))
        xg = rng.normal(size=d_g); xu = rng.normal(size=d_u)
        recs.append({
            "uid": f"{fi}-{i}",
            "response": float(rng.uniform() > 0.5),
            "metadataMap": {"userId": f"user{u}"},
            "features": [
                {"name": f"g{j}", "term": "", "value": float(xg[j])}
                for j in range(d_g)
            ],
            "userFeatures": [
                {"name": f"u{j}", "term": "", "value": float(xu[j])}
                for j in range(d_u)
            ],
        })
    write_container(f"{tmp}/part-{fi}.avro", schema, recs)
    del recs

from photon_ml_tpu.game.streaming import train_streaming_game

shards = [FeatureShardConfiguration("globalShard", ["features"]),
          FeatureShardConfiguration("userShard", ["userFeatures"])]
fe = {"global": FixedEffectDataConfiguration("globalShard")}
re = {"per-user": RandomEffectDataConfiguration(
    "userId", "userShard", projector_type=ProjectorType.IDENTITY)}
combo = {"global": GLMOptimizationConfiguration.parse("8,1e-5,0.5,1,LBFGS,L2"),
         "per-user": GLMOptimizationConfiguration.parse("8,1e-5,1.0,1,LBFGS,L2")}
BUDGET = 2 << 20
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
res, extras = train_streaming_game(
    [tmp], shards, fe, re, combo, TaskType.LOGISTIC_REGRESSION,
    num_iterations=1, memory_budget_bytes=BUDGET)
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
assert extras["store"].count >= 3, extras["store"].count
print("CHUNKS", extras["store"].count)
print("DELTA_KB", peak - base)
"""
        out = subprocess.run(
            [sys.executable, "-c", script, str(tmp_path)],
            capture_output=True, text=True, timeout=560,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert out.returncode == 0, out.stderr[-3000:]
        delta_kb = int(out.stdout.split("DELTA_KB")[-1].strip())
        # 48k rows of record dicts are >200 MB transient; training's RSS
        # growth over the post-import/post-datagen base must stay in the
        # budget + jit/compile + model class (NOT the dataset class).
        # Budget is 2 MB; allow 96 MB of interpreter/XLA slack.
        assert delta_kb < 96_000, delta_kb
