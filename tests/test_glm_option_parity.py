"""Reference CLI option parity: field-name conventions, feature-dimension,
optimization tracker output, deprecated/obviated flags
(OptionNames.scala surface)."""


import numpy as np
import pytest

from photon_ml_tpu.cli.glm_driver import GLMDriver, GLMParams, params_from_args
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container


@pytest.fixture
def rng():
    return np.random.default_rng(17)


def _write_response_prediction_avro(path, rng, n=100, d=5):
    """RESPONSE_PREDICTION convention: the response field is named
    ``response`` (avro/ResponsePredictionFieldNames.scala)."""
    schema = {
        "name": "ResponsePrediction", "type": "record",
        "fields": [
            {"name": "response", "type": "double"},
            {
                "name": "features",
                "type": {"type": "array", "items": schemas.FEATURE_AVRO},
            },
            {"name": "offset", "type": ["null", "double"], "default": None},
            {"name": "weight", "type": ["null", "double"], "default": None},
        ],
    }
    w = np.linspace(-1, 1, d)
    recs = []
    for _ in range(n):
        x = rng.normal(size=d)
        y = float(1 / (1 + np.exp(-x @ w)) > rng.uniform())
        recs.append({
            "response": y,
            "features": [
                {"name": f"f{j}", "term": "", "value": float(x[j])}
                for j in range(d)
            ],
            "offset": None,
            "weight": None,
        })
    write_container(path, schema, recs)


class TestFieldNames:
    def test_response_prediction_trains(self, tmp_path, rng):
        train = tmp_path / "train"
        train.mkdir()
        _write_response_prediction_avro(str(train / "p.avro"), rng)
        params = GLMParams(
            train_dir=str(train),
            output_dir=str(tmp_path / "out"),
            field_names="RESPONSE_PREDICTION",
            regularization_weights=[1.0],
            distributed="off",
        )
        driver = GLMDriver(params)
        driver.run()
        assert driver.models
        labels = np.asarray(driver._data.batch.labels)
        assert set(np.unique(labels)) <= {0.0, 1.0}

    def test_training_example_files_skip_native_for_response(self, tmp_path, rng):
        """A RESPONSE_PREDICTION file read with TRAINING_EXAMPLE field
        names has no 'label' field -> loud failure, not silent zeros."""
        from photon_ml_tpu.io.input_format import AvroInputDataFormat

        train = tmp_path / "train"
        train.mkdir()
        _write_response_prediction_avro(str(train / "p.avro"), rng, n=10)
        fmt = AvroInputDataFormat(field_names="TRAINING_EXAMPLE")
        with pytest.raises(KeyError):
            fmt.load([str(train)])

    def test_unknown_field_names_rejected(self):
        from photon_ml_tpu.io.input_format import AvroInputDataFormat

        with pytest.raises(ValueError, match="field names"):
            AvroInputDataFormat(field_names="WAT")


class TestFormatRouting:
    def test_legacy_format_values_route_to_file_format(self, tmp_path):
        p = params_from_args([
            "--training-data-directory", "x", "--output-directory", "y",
            "--format", "LIBSVM",
        ])
        assert p.input_format == "LIBSVM"
        assert p.field_names == "TRAINING_EXAMPLE"

    def test_field_names_format(self):
        p = params_from_args([
            "--training-data-directory", "x", "--output-directory", "y",
            "--format", "RESPONSE_PREDICTION",
            "--input-file-format", "AVRO",
        ])
        assert p.input_format == "AVRO"
        assert p.field_names == "RESPONSE_PREDICTION"

    def test_training_diagnostics_exclusive(self):
        with pytest.raises(ValueError, match="not supported"):
            params_from_args([
                "--training-data-directory", "x", "--output-directory", "y",
                "--training-diagnostics", "true",
                "--diagnostic-mode", "ALL",
            ])
        p = params_from_args([
            "--training-data-directory", "x", "--output-directory", "y",
            "--training-diagnostics", "true",
        ])
        assert p.diagnostic_mode.name == "ALL"

    def test_spark_only_flags_accepted(self):
        p = params_from_args([
            "--training-data-directory", "x", "--output-directory", "y",
            "--kryo", "true", "--min-partitions", "64",
            "--tree-aggregate-depth", "2",
        ])
        assert p.train_dir == "x"


class TestFeatureDimension:
    def test_libsvm_identity_map(self, tmp_path, rng):
        train = tmp_path / "train"
        train.mkdir()
        lines = []
        for _ in range(60):
            x = rng.normal(size=4)
            y = 1 if x.sum() > 0 else -1
            lines.append(
                f"{y} " + " ".join(f"{j + 1}:{x[j]:.4f}" for j in range(4))
            )
        (train / "data.txt").write_text("\n".join(lines) + "\n")
        params = params_from_args([
            "--training-data-directory", str(train),
            "--output-directory", str(tmp_path / "out"),
            "--format", "LIBSVM",
            "--feature-dimension", "10",  # upper bound, not scanned
            "--regularization-weights", "1.0",
        ])
        driver = GLMDriver(params)
        driver.run()
        # 10 declared features + intercept
        assert driver._data.num_features == 11
        assert driver._data.intercept_index == 10


class TestOptimizationTracker:
    def test_log_written_and_disable(self, tmp_path, rng):
        train = tmp_path / "train"
        train.mkdir()
        _write_response_prediction_avro(str(train / "p.avro"), rng)
        params = GLMParams(
            train_dir=str(train),
            output_dir=str(tmp_path / "out"),
            field_names="RESPONSE_PREDICTION",
            regularization_weights=[1.0, 10.0],
            distributed="off",
        )
        GLMDriver(params).run()
        log = tmp_path / "out" / "optimization-log.txt"
        text = log.read_text()
        assert "lambda=1.0" in text and "lambda=10.0" in text
        assert "|grad|=" in text

        params2 = GLMParams(
            train_dir=str(train),
            output_dir=str(tmp_path / "out2"),
            field_names="RESPONSE_PREDICTION",
            enable_optimization_tracker=False,
            distributed="off",
        )
        GLMDriver(params2).run()
        assert not (tmp_path / "out2" / "optimization-log.txt").exists()


class TestReviewRegressions:
    def test_diagnostic_mode_equals_form_conflict(self):
        with pytest.raises(ValueError, match="not supported"):
            params_from_args([
                "--training-data-directory", "x", "--output-directory", "y",
                "--training-diagnostics", "false",
                "--diagnostic-mode=ALL",
            ])

    def test_feature_dimension_with_avro_rejected(self):
        p = params_from_args([
            "--training-data-directory", "x", "--output-directory", "y",
            "--feature-dimension", "10",
        ])
        with pytest.raises(ValueError, match="LIBSVM"):
            p.validate()

    def test_identity_map_respects_selected_features(self, tmp_path):
        from photon_ml_tpu.io.input_format import LibSVMInputDataFormat
        from photon_ml_tpu.utils.index_map import feature_key

        (tmp_path / "d.txt").write_text("1 1:2.0 2:3.0 3:4.0\n")
        fmt = LibSVMInputDataFormat(
            add_intercept=False,
            feature_dimension=5,
            selected_features=[feature_key("0"), feature_key("2")],
        )
        loaded = fmt.load([str(tmp_path)])
        vals = np.asarray(loaded.batch.values)[0]
        # only 1-based ids 1 and 3 (0-based 0 and 2) survive the filter
        assert sorted(v for v in vals.tolist() if v) == [2.0, 4.0]


class TestTileCacheOption:
    def test_tile_cache_dir_flag_and_default(self):
        p = params_from_args([
            "--training-data-directory", "x", "--output-directory", "y",
        ])
        assert p.tile_cache_dir is None  # env-var / off default
        p = params_from_args([
            "--training-data-directory", "x", "--output-directory", "y",
            "--tile-cache-dir", "/scratch/tiles",
        ])
        assert p.tile_cache_dir == "/scratch/tiles"


class TestDiagnosticReservoirBudget:
    def test_byte_budget_scales_rows_down(self):
        from photon_ml_tpu.cli.glm_driver import budgeted_reservoir_rows

        # narrow rows: the row cap binds, not the byte budget
        assert budgeted_reservoir_rows(100_000, 256 << 20, 16) == 100_000
        # wide rows (max_nnz 4096 -> ~32 KiB/row): the byte budget binds
        wide = budgeted_reservoir_rows(100_000, 256 << 20, 4096)
        assert 1 <= wide < 100_000
        assert wide * (4096 * 8 + 12) <= 256 << 20
        # pathologically wide rows still sample at least one row
        assert budgeted_reservoir_rows(100_000, 1024, 1 << 20) == 1

    def test_reservoir_params_validated(self, tmp_path):
        p = GLMParams(
            train_dir="x", output_dir=str(tmp_path / "o"),
            diagnostic_reservoir_rows=0,
        )
        with pytest.raises(ValueError, match="reservoir-rows"):
            p.validate()
        p = GLMParams(
            train_dir="x", output_dir=str(tmp_path / "o"),
            diagnostic_reservoir_bytes=0,
        )
        with pytest.raises(ValueError, match="reservoir-bytes"):
            p.validate()
