"""Diagnostics tests: bootstrap CIs, Hosmer-Lemeshow on calibrated vs
miscalibrated models, Kendall tau, importance, fitting curves, HTML
report rendering, driver DIAGNOSED stage, checkpoint/resume, events.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.data.batch import make_dense_batch
from photon_ml_tpu.diagnostics import (
    Document,
    Chapter,
    Section,
    Table,
    Text,
    LinePlot,
    bootstrap_training_diagnostic,
    feature_importance_diagnostic,
    fitting_diagnostic,
    hosmer_lemeshow_diagnostic,
    kendall_tau_diagnostic,
    render_html,
)
from photon_ml_tpu.events import (
    EventEmitter,
    EventListener,
    PhotonOptimizationLogEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.models import Coefficients, logistic_regression_model
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.task import TaskType

# Bootstrap/fitting diagnostics retrain many models: integration tier
pytestmark = pytest.mark.slow


def logistic_batch(rng, n=400, d=5, w=None):
    x = rng.normal(size=(n, d)).astype(np.float32)
    x[:, -1] = 1.0
    if w is None:
        w = rng.normal(size=d).astype(np.float32)
    y = (1 / (1 + np.exp(-x @ w)) > rng.uniform(size=n)).astype(np.float32)
    return make_dense_batch(x, y), w


def fit(batch, d=5):
    problem = create_glm_problem(TaskType.LOGISTIC_REGRESSION, d)
    coefficients, _ = problem.run(batch, reg_weight=1e-3)
    return problem.create_model(coefficients)


class TestHosmerLemeshow:
    def test_calibrated_model_passes(self, rng):
        batch, _ = logistic_batch(rng, n=2000)
        model = fit(batch)
        hl = hosmer_lemeshow_diagnostic(model, batch)
        assert hl.degrees_of_freedom == 8
        assert hl.p_value > 0.01, (hl.chi_square, hl.p_value)

    def test_miscalibrated_model_fails(self, rng):
        batch, w = logistic_batch(rng, n=2000)
        bad = logistic_regression_model(
            Coefficients(jnp.asarray(3.0 * np.asarray(w)))
        )
        hl = hosmer_lemeshow_diagnostic(bad, batch)
        assert hl.p_value < 0.01

    def test_rejects_regression(self, rng):
        batch, _ = logistic_batch(rng)
        from photon_ml_tpu.models import linear_regression_model

        bad = linear_regression_model(Coefficients(jnp.zeros(5)))
        with pytest.raises(ValueError):
            hosmer_lemeshow_diagnostic(bad, batch)


class TestKendallTau:
    def test_well_specified(self, rng):
        batch, _ = logistic_batch(rng, n=800)
        model = fit(batch)
        kt = kendall_tau_diagnostic(model, batch)
        assert np.isfinite(kt.tau)


class TestImportance:
    def test_orders_by_magnitude(self):
        model = logistic_regression_model(
            Coefficients(jnp.asarray([0.1, -5.0, 1.0]))
        )
        rep = feature_importance_diagnostic(
            model, np.array([1.0, 1.0, 1.0]), np.array([1.0, 1.0, 1.0])
        )
        assert rep.expected_magnitude[0][0] == 1
        assert rep.variance_magnitude[0][0] == 1


class TestBootstrap:
    def test_intervals_cover_estimate(self, rng):
        batch, _ = logistic_batch(rng, n=600)
        model = fit(batch)
        rep = bootstrap_training_diagnostic(
            batch, fit, lambda m: {"norm": float(jnp.linalg.norm(m.means))},
            num_samples=5,
        )
        assert rep.coefficient_intervals.shape == (5, 4)
        mean, std, lo, hi = rep.coefficient_intervals.T
        assert np.all(lo <= hi)
        # full-data fit should mostly land within the bootstrap ranges
        w = np.asarray(model.means)
        inside = np.sum((w >= lo - 3 * std - 1e-3) & (w <= hi + 3 * std + 1e-3))
        assert inside >= 4
        assert "norm" in rep.metrics_distribution


class TestFitting:
    def test_curves_monotone_data(self, rng):
        train, w = logistic_batch(rng, n=600)
        test, _ = logistic_batch(rng, n=300, w=w)

        def metrics(m, b):
            from photon_ml_tpu.evaluation import area_under_roc_curve
            from photon_ml_tpu.models.glm import compute_margins

            z = compute_margins(m.means, b)
            return {"AUC": float(area_under_roc_curve(z, b.labels, b.weights))}

        rep = fitting_diagnostic(train, test, fit, metrics, num_portions=4)
        assert len(rep.portions) == 4
        assert all(len(v) == 4 for v in rep.train_metrics.values())
        # more data should not hurt test AUC much: last >= first - slack
        assert rep.test_metrics["AUC"][-1] >= rep.test_metrics["AUC"][0] - 0.1


class TestReporting:
    def test_render_html(self):
        doc = Document(
            "t", [Chapter("c", [Section("s", [
                Text("hello <world>"),
                Table(["a", "b"], [["1", "2"]], caption="cap"),
                LinePlot([0, 1, 2], [("s1", [0.0, 1.0, 0.5])], title="p"),
            ])])]
        )
        html = render_html(doc)
        assert "hello &lt;world&gt;" in html
        assert "<table>" in html and "<svg" in html and "polyline" in html


class TestDriverDiagnoseStage:
    def test_end_to_end_with_report(self, tmp_path, rng):
        from tests.test_glm_driver import synth_avro
        from photon_ml_tpu.cli.glm_driver import (
            DiagnosticMode,
            DriverStage,
            GLMDriver,
            GLMParams,
        )

        train = tmp_path / "train"; train.mkdir()
        val = tmp_path / "val"; val.mkdir()
        synth_avro(str(train / "p.avro"), rng, n=200)
        synth_avro(str(val / "p.avro"), rng, n=100)
        params = GLMParams(
            train_dir=str(train),
            validate_dir=str(val),
            output_dir=str(tmp_path / "out"),
            regularization_weights=[1.0],
            diagnostic_mode=DiagnosticMode.ALL,
        )
        driver = GLMDriver(params)
        driver.run()
        assert DriverStage.DIAGNOSED in driver.stage_history
        report = tmp_path / "out" / "model-diagnostics" / "report.html"
        assert report.is_file()
        content = report.read_text()
        assert "Hosmer-Lemeshow" in content and "Bootstrap" in content
        assert "Learning curves" in content


class TestCheckpointing:
    def test_coordinate_descent_resume(self, tmp_path, rng):
        from tests.test_game import SHARDS, make_records
        from photon_ml_tpu.game import (
            CoordinateDescent,
            FixedEffectCoordinate,
            RandomEffectCoordinate,
            RandomEffectDataConfiguration,
            RandomEffectOptimizationProblem,
            build_game_dataset,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.ops.losses import LOGISTIC
        from photon_ml_tpu.optim import OptimizerConfig, RegularizationContext, RegularizationType
        from photon_ml_tpu.utils.checkpoint import TrainingCheckpointer

        recs, _, _ = make_records(rng, n=150, n_users=5)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        def coords():
            return {
                "global": FixedEffectCoordinate(
                    name="global", dataset=ds,
                    problem=create_glm_problem(
                        TaskType.LOGISTIC_REGRESSION,
                        ds.shards["globalShard"].dim,
                        config=OptimizerConfig(max_iter=15),
                        regularization=RegularizationContext(RegularizationType.L2),
                    ),
                    feature_shard_id="globalShard", reg_weight=0.1,
                ),
                "per-user": RandomEffectCoordinate(
                    name="per-user", dataset=ds, re_dataset=red,
                    problem=RandomEffectOptimizationProblem(
                        LOGISTIC, OptimizerConfig(max_iter=15),
                        RegularizationContext(RegularizationType.L2), 1.0,
                    ),
                ),
            }

        ckpt_dir = str(tmp_path / "ckpt")
        cp1 = TrainingCheckpointer(ckpt_dir)
        cd1 = CoordinateDescent(
            coords(), ds, TaskType.LOGISTIC_REGRESSION, checkpointer=cp1
        )
        r1 = cd1.run(2)
        cp1.close()
        assert TrainingCheckpointer(ckpt_dir).latest_step() == 2

        # resume: a fresh run with the same checkpointer continues at iter 2
        cp2 = TrainingCheckpointer(ckpt_dir)
        cd2 = CoordinateDescent(
            coords(), ds, TaskType.LOGISTIC_REGRESSION, checkpointer=cp2
        )
        r2 = cd2.run(3)  # only iteration 3 actually runs
        cp2.close()
        assert len(r2.objective_history) == 1
        assert r2.objective_history[-1] <= r1.objective_history[-1] + 1e-5


class TestPreemption:
    def test_sigterm_sets_flag_and_chains(self):
        import os
        import signal

        from photon_ml_tpu.utils.preemption import PreemptionGuard

        outer = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: outer.append(s))
        try:
            with PreemptionGuard() as guard:
                assert not guard.requested
                os.kill(os.getpid(), signal.SIGTERM)
                assert guard.requested
                assert outer == [signal.SIGTERM]  # chained to prior handler
            # uninstalled: prior handler restored
            os.kill(os.getpid(), signal.SIGTERM)
            assert outer == [signal.SIGTERM] * 2
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_coordinate_descent_stops_at_boundary_and_resumes(
        self, tmp_path, rng
    ):
        from tests.test_game import SHARDS, make_records
        from photon_ml_tpu.game import (
            CoordinateDescent,
            FixedEffectCoordinate,
            RandomEffectDataConfiguration,
            build_game_dataset,
        )
        from photon_ml_tpu.optim import (
            OptimizerConfig,
            RegularizationContext,
            RegularizationType,
        )
        from photon_ml_tpu.utils.checkpoint import TrainingCheckpointer
        from photon_ml_tpu.utils.preemption import PreemptionGuard

        recs, _, _ = make_records(rng, n=120, n_users=4)
        ds = build_game_dataset(recs, SHARDS, ["userId"])

        def coords():
            return {
                "global": FixedEffectCoordinate(
                    name="global", dataset=ds,
                    problem=create_glm_problem(
                        TaskType.LOGISTIC_REGRESSION,
                        ds.shards["globalShard"].dim,
                        config=OptimizerConfig(max_iter=10),
                        regularization=RegularizationContext(
                            RegularizationType.L2
                        ),
                    ),
                    feature_shard_id="globalShard", reg_weight=0.1,
                ),
            }

        guard = PreemptionGuard()
        guard.request()  # preempt before the run: stop after iteration 1
        ckpt = str(tmp_path / "ckpt")
        cp = TrainingCheckpointer(ckpt)
        r = CoordinateDescent(
            coords(), ds, TaskType.LOGISTIC_REGRESSION,
            checkpointer=cp, preemption_guard=guard,
        ).run(3)
        cp.close()
        assert r.preempted
        assert len(r.objective_history) == 1
        assert TrainingCheckpointer(ckpt).latest_step() == 1

        # restarted "job": resumes at iteration 2 and finishes the plan
        cp2 = TrainingCheckpointer(ckpt)
        r2 = CoordinateDescent(
            coords(), ds, TaskType.LOGISTIC_REGRESSION,
            checkpointer=cp2, preemption_guard=PreemptionGuard(),
        ).run(3)
        cp2.close()
        assert not r2.preempted
        assert len(r2.objective_history) == 2  # iterations 2 and 3


class TestEvents:
    def test_emitter_and_listener(self):
        seen = []

        class L(EventListener):
            def on_event(self, e):
                seen.append(e)

        em = EventEmitter()
        em.register(L())
        em.send(TrainingStartEvent("job"))
        em.send(PhotonOptimizationLogEvent(reg_weight=1.0, iterations=5))
        assert len(seen) == 2
        assert isinstance(seen[0], TrainingStartEvent)
        em.close()


class TestTextRenderStrategy:
    def test_render_text(self):
        from photon_ml_tpu.diagnostics.reporting import (
            Chapter, Document, LinePlot, Section, Table, Text, render_text,
        )

        doc = Document("Report", [
            Chapter("Model", [
                Section("Summary", [
                    Text("hello world"),
                    Table(["name", "value"], [["auc", "0.91"], ["n", "120"]],
                          caption="metrics"),
                    LinePlot([1, 2, 3], [("loss", [3.0, 2.0, 1.5])],
                             title="learning curve"),
                ]),
            ]),
        ])
        text = render_text(doc)
        assert "Report" in text and "=====" in text
        assert "## Summary" in text
        assert "hello world" in text
        assert "auc   0.91" in text
        assert "[plot] learning curve" in text
        assert "last=1.5" in text

    def test_driver_writes_text_report(self, tmp_path, rng):
        import os

        from photon_ml_tpu.cli.glm_driver import DiagnosticMode, GLMDriver, GLMParams

        train = tmp_path / "train"
        train.mkdir()
        import sys
        sys.path.insert(0, os.path.dirname(__file__))
        from test_glm_driver import synth_avro

        synth_avro(str(train / "p.avro"), rng, n=120)
        params = GLMParams(
            train_dir=str(train),
            output_dir=str(tmp_path / "out"),
            regularization_weights=[1.0],
            diagnostic_mode=DiagnosticMode.TRAIN,
            distributed="off",
        )
        GLMDriver(params).run()
        base = tmp_path / "out" / "model-diagnostics"
        assert (base / "report.html").is_file()
        txt = (base / "report.txt").read_text()
        assert "=" in txt and "##" in txt
