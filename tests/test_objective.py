"""GLMObjective vs dense numpy oracles, incl. normalization algebra and
sparse==dense equivalence (reference: function/DistributedGLMLossFunction and
aggregator integration tests)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data.batch import make_dense_batch, make_sparse_batch
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.normalization import (
    NormalizationContext,
    NormalizationType,
    build_normalization,
)
from photon_ml_tpu.ops.objective import GLMObjective

DIM = 12
N = 37


def _data(rng, sparse_frac=0.6):
    x = rng.normal(size=(N, DIM)).astype(np.float32)
    mask = rng.uniform(size=(N, DIM)) < sparse_frac
    x = np.where(mask, 0.0, x)
    x[:, 0] = 1.0  # intercept column
    y = (rng.uniform(size=N) < 0.5).astype(np.float32)
    off = rng.normal(size=N).astype(np.float32) * 0.1
    w = rng.uniform(0.5, 2.0, size=N).astype(np.float32)
    return x, y, off, w


def _to_sparse_rows(x):
    rows = []
    for i in range(x.shape[0]):
        ix = np.nonzero(x[i])[0]
        rows.append((ix.tolist(), x[i, ix].tolist()))
    return rows


def _np_oracle(x, y, off, w, coef, loss, l2, factor=None, shift=None):
    """Dense numpy objective on explicitly transformed features."""
    xe = x.copy()
    if shift is not None:
        xe = xe - shift[None, :]
    if factor is not None:
        xe = xe * factor[None, :]
    z = xe @ coef + off
    if loss is losses.LOGISTIC:
        lv = np.logaddexp(0, z) - y * z
        s = 1 / (1 + np.exp(-z))
        d1 = s - y
        d2 = s * (1 - s)
    elif loss is losses.LINEAR:
        lv = 0.5 * (z - y) ** 2
        d1 = z - y
        d2 = np.ones_like(z)
    else:
        lv = np.exp(z) - y * z
        d1 = np.exp(z) - y
        d2 = np.exp(z)
    val = np.sum(w * lv) + 0.5 * l2 * coef @ coef
    grad = xe.T @ (w * d1) + l2 * coef
    hdiag = (xe**2).T @ (w * d2) + l2
    return val, grad, d2, xe, hdiag


@pytest.mark.parametrize("kernel", ["scatter", "tiled"])
@pytest.mark.parametrize("loss", [losses.LOGISTIC, losses.LINEAR, losses.POISSON], ids=lambda l: l.name)
@pytest.mark.parametrize("norm", ["none", "scale", "standardize"])
def test_value_grad_hv_hdiag_vs_oracle(rng, loss, norm, kernel):
    x, y, off, w = _data(rng)
    coef = rng.normal(size=DIM).astype(np.float32) * 0.3
    d = rng.normal(size=DIM).astype(np.float32)
    l2 = 0.7

    factor = shift = None
    ctx = NormalizationContext()
    if norm == "scale":
        factor = (1.0 / (np.abs(x).max(axis=0) + 0.5)).astype(np.float32)
        ctx = NormalizationContext(factor=jnp.asarray(factor), shift=None)
    elif norm == "standardize":
        factor = (1.0 / (x.std(axis=0) + 0.5)).astype(np.float32)
        shift = x.mean(axis=0).astype(np.float32)
        shift[0] = 0.0
        factor[0] = 1.0
        ctx = NormalizationContext(factor=jnp.asarray(factor), shift=jnp.asarray(shift))

    val_o, grad_o, d2_o, xe, hdiag_o = _np_oracle(x, y, off, w, coef, loss, l2, factor, shift)
    hv_o = xe.T @ ((w * d2_o) * (xe @ d)) + l2 * d

    batch = make_sparse_batch(_to_sparse_rows(x), y, off, w)
    if kernel == "tiled":
        from photon_ml_tpu.ops.tiled_sparse import (
            TileParams,
            TiledGLMObjective,
            tiled_batch_from_sparse,
        )

        obj = TiledGLMObjective(
            loss, DIM, norm=ctx, interpret=True, mxu="highest"
        )
        batch = tiled_batch_from_sparse(
            batch, DIM, params=TileParams(8, 8, 32)
        )
    else:
        obj = GLMObjective(loss=loss, dim=DIM, norm=ctx)

    val = obj.value(jnp.asarray(coef), batch, l2)
    v2, grad = obj.value_and_gradient(jnp.asarray(coef), batch, l2)
    hv = obj.hessian_vector(jnp.asarray(coef), jnp.asarray(d), batch, l2)
    hdiag = obj.hessian_diagonal(jnp.asarray(coef), batch, l2)

    np.testing.assert_allclose(float(val), val_o, rtol=2e-4)
    np.testing.assert_allclose(float(v2), val_o, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(grad), grad_o, rtol=3e-3, atol=3e-3)
    np.testing.assert_allclose(np.asarray(hv), hv_o, rtol=3e-3, atol=3e-3)
    np.testing.assert_allclose(np.asarray(hdiag), hdiag_o, rtol=3e-3, atol=3e-3)


def test_sparse_equals_dense(rng):
    x, y, off, w = _data(rng)
    coef = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    obj = GLMObjective(loss=losses.LOGISTIC, dim=DIM)
    sb = make_sparse_batch(_to_sparse_rows(x), y, off, w)
    db = make_dense_batch(x, y, off, w)
    vs, gs = obj.value_and_gradient(coef, sb, 0.1)
    vd, gd = obj.value_and_gradient(coef, db, 0.1)
    np.testing.assert_allclose(float(vs), float(vd), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gd), rtol=1e-4, atol=1e-5)


def test_padding_rows_are_inert(rng):
    x, y, off, w = _data(rng)
    obj = GLMObjective(loss=losses.LOGISTIC, dim=DIM)
    coef = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    b_tight = make_sparse_batch(_to_sparse_rows(x), y, off, w, pad_rows_to=1)
    b_padded = make_sparse_batch(_to_sparse_rows(x), y, off, w, pad_rows_to=64)
    assert b_padded.num_rows > b_tight.num_rows
    v1, g1 = obj.value_and_gradient(coef, b_tight, 0.3)
    v2, g2 = obj.value_and_gradient(coef, b_padded, 0.3)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5, atol=1e-6)


def test_gradient_matches_jax_autodiff(rng):
    """The hand-fused gradient must equal jax.grad of the value."""
    x, y, off, w = _data(rng)
    ctx = NormalizationContext(
        factor=jnp.asarray(rng.uniform(0.5, 2, DIM).astype(np.float32)),
        shift=jnp.asarray(rng.normal(size=DIM).astype(np.float32) * 0.1),
    )
    obj = GLMObjective(loss=losses.LOGISTIC, dim=DIM, norm=ctx)
    batch = make_sparse_batch(_to_sparse_rows(x), y, off, w)
    coef = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    g_auto = jax.grad(lambda c: obj.value(c, batch, 0.5))(coef)
    _, g_manual = obj.value_and_gradient(coef, batch, 0.5)
    np.testing.assert_allclose(np.asarray(g_manual), np.asarray(g_auto), rtol=1e-4, atol=1e-5)


def test_hessian_vector_matches_autodiff(rng):
    x, y, off, w = _data(rng)
    obj = GLMObjective(loss=losses.POISSON, dim=DIM)
    batch = make_sparse_batch(_to_sparse_rows(x), y, off, w)
    coef = jnp.asarray(rng.normal(size=DIM).astype(np.float32) * 0.1)
    d = jnp.asarray(rng.normal(size=DIM).astype(np.float32))
    hv_auto = jax.jvp(jax.grad(lambda c: obj.value(c, batch, 0.2)), (coef,), (d,))[1]
    hv_manual = obj.hessian_vector(coef, d, batch, 0.2)
    np.testing.assert_allclose(np.asarray(hv_manual), np.asarray(hv_auto), rtol=2e-3, atol=2e-3)


def test_build_normalization_types(rng):
    mean = np.asarray([1.0, 2.0, 0.0], np.float32)
    std = np.asarray([2.0, 0.0, 1.0], np.float32)
    mx = np.asarray([4.0, 2.0, 0.0], np.float32)
    ctx = build_normalization(
        NormalizationType.STANDARDIZATION, mean=mean, std=std, max_magnitude=mx, intercept_index=2
    )
    np.testing.assert_allclose(np.asarray(ctx.factor), [0.5, 1.0, 1.0])
    np.testing.assert_allclose(np.asarray(ctx.shift), [1.0, 2.0, 0.0])
    ctx2 = build_normalization(
        NormalizationType.SCALE_WITH_MAX_MAGNITUDE, mean=mean, std=std, max_magnitude=mx
    )
    assert ctx2.shift is None
    np.testing.assert_allclose(np.asarray(ctx2.factor), [0.25, 0.5, 1.0])
    assert build_normalization(
        NormalizationType.NONE, mean=mean, std=std, max_magnitude=mx
    ).is_identity


def test_tron_and_box_through_problem_layer_with_tiled_kernel(rng):
    """TRON (hessian_vector-driven) and box constraints must work through
    GLMOptimizationProblem with the tiled objective — same contract as the
    scatter kernel (task: tiled/scatter construction switch parity)."""
    from photon_ml_tpu.optim.common import BoxConstraints
    from photon_ml_tpu.optim.config import OptimizerConfig, OptimizerType
    from photon_ml_tpu.optim.problem import create_glm_problem
    from photon_ml_tpu.ops.tiled_sparse import tiled_batch_from_sparse
    from photon_ml_tpu.task import TaskType

    x, y, off, w = _data(rng)
    batch = make_sparse_batch(_to_sparse_rows(x), y, off, w)
    lower = np.full(DIM, -0.5, np.float32)
    upper = np.full(DIM, 0.5, np.float32)
    box = BoxConstraints(jnp.asarray(lower), jnp.asarray(upper))
    config = OptimizerConfig(optimizer_type=OptimizerType.TRON, max_iter=10)

    results = {}
    for kernel in ("scatter", "tiled"):
        problem = create_glm_problem(
            TaskType.LOGISTIC_REGRESSION, DIM,
            config=config, box=box, kernel=kernel,
        )
        b = (
            tiled_batch_from_sparse(batch, DIM)
            if kernel == "tiled" else batch
        )
        coefficients, result = problem.run(b, reg_weight=0.5)
        means = np.asarray(coefficients.means)
        assert np.all(means >= lower - 1e-6) and np.all(means <= upper + 1e-6)
        results[kernel] = (means, float(result.value))
    np.testing.assert_allclose(
        results["tiled"][0], results["scatter"][0], rtol=0.02, atol=1e-2
    )
    np.testing.assert_allclose(
        results["tiled"][1], results["scatter"][1], rtol=1e-3
    )
