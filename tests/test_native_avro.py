"""Native Avro column decoder vs the pure-Python codec: byte-identical
container files must produce identical columns (labels, offsets, weights,
feature bags, metadataMap ids) through both paths."""

import numpy as np
import pytest

from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import read_container, write_container
from photon_ml_tpu.io import native_avro


pytestmark = pytest.mark.skipif(
    not native_avro.available(), reason="native avro build unavailable"
)


def _training_schema():
    schema = dict(schemas.TRAINING_EXAMPLE_AVRO)
    return schema


def _write_fixture(path, rng, n=500, codec="deflate"):
    recs = []
    for i in range(n):
        feats = [
            {
                "name": f"f{int(j)}",
                "term": "" if j % 2 == 0 else f"t{int(j)}",
                "value": float(rng.normal()),
            }
            for j in rng.integers(0, 50, size=rng.integers(0, 8))
        ]
        rec = {
            "uid": f"u{i}",
            "label": float(rng.integers(0, 2)),
            "features": feats,
            "weight": float(rng.uniform(0.5, 2.0)),
            "offset": float(rng.normal()),
            "metadataMap": {"queryId": f"q{i % 7}", "other": "x"},
        }
        if i % 11 == 0:
            rec["offset"] = None  # optional field exercised
            rec["metadataMap"] = None
        recs.append(rec)
    write_container(path, _training_schema(), recs, codec=codec)
    return recs


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_matches_python_codec(tmp_path, rng, codec):
    path = str(tmp_path / "train.avro")
    recs = _write_fixture(path, rng, codec=codec)

    plan = native_avro.plan_for_file(
        path,
        numeric_fields=["label", "offset", "weight"],
        string_fields=["uid"],
        bag_fields=["features"],
        map_field="metadataMap",
        map_keys=["queryId", "missingKey"],
    )
    cols = native_avro.decode_columns(path, plan)
    assert cols.num_records == len(recs)

    # scalars
    np.testing.assert_array_equal(
        cols.f64("label"), np.asarray([r["label"] for r in recs])
    )
    np.testing.assert_array_equal(
        cols.f64("weight"), np.asarray([r["weight"] for r in recs])
    )
    offs = cols.f64("offset")
    for i, r in enumerate(recs):
        if r["offset"] is None:
            assert np.isnan(offs[i])
        else:
            assert offs[i] == r["offset"]

    # strings
    uid_ids = cols.str_ids("uid")
    assert [cols.strings[j] for j in uid_ids] == [r["uid"] for r in recs]

    # metadataMap
    qids = cols.map_ids("queryId")
    missing = cols.map_ids("missingKey")
    assert np.all(missing == -1)
    for i, r in enumerate(recs):
        if r["metadataMap"] is None:
            assert qids[i] == -1
        else:
            assert cols.strings[qids[i]] == r["metadataMap"]["queryId"]

    # feature bag: row_ptr + (name TAB term) keys + values
    row_ptr, key_ids, values = cols.bag("features")
    assert row_ptr[0] == 0 and row_ptr[-1] == len(key_ids)
    for i, r in enumerate(recs):
        lo, hi = int(row_ptr[i]), int(row_ptr[i + 1])
        got = [
            (cols.strings[key_ids[j]], values[j]) for j in range(lo, hi)
        ]
        want = [
            (f["name"] + "\t" + f["term"], f["value"]) for f in r["features"]
        ]
        assert got == want

    # cross-check the file itself still reads through the Python codec
    _, it = read_container(path)
    assert sum(1 for _ in it) == len(recs)


def test_unsupported_shape_raises_plan_error(tmp_path, rng):
    schema = {
        "name": "Odd", "type": "record",
        "fields": [{"name": "blob", "type": {"type": "fixed", "name": "F", "size": 4}}],
    }
    path = str(tmp_path / "odd.avro")
    write_container(path, schema, [{"blob": b"abcd"}], codec="null")
    with pytest.raises(native_avro.PlanError):
        native_avro.plan_for_file(path, numeric_fields=[])


def test_throughput_exceeds_python_codec(tmp_path, rng):
    """Not a benchmark — just a sanity floor: the native path should beat
    the record-at-a-time Python codec comfortably on a mid-size file."""
    import time

    path = str(tmp_path / "big.avro")
    _write_fixture(path, rng, n=20_000)

    t0 = time.perf_counter()
    plan = native_avro.plan_for_file(
        path, numeric_fields=["label"], bag_fields=["features"]
    )
    cols = native_avro.decode_columns(path, plan)
    native_s = time.perf_counter() - t0
    assert cols.num_records == 20_000

    t0 = time.perf_counter()
    _, it = read_container(path)
    n = sum(1 for _ in it)
    python_s = time.perf_counter() - t0
    assert n == 20_000
    assert native_s < python_s, (native_s, python_s)


def test_input_format_parity_with_python_path(tmp_path, rng, monkeypatch):
    """AvroInputDataFormat must produce the IDENTICAL batch through the
    native fast path and the record-at-a-time Python fallback."""
    from photon_ml_tpu.io.input_format import AvroInputDataFormat

    path = str(tmp_path / "t.avro")
    _write_fixture(path, rng, n=300)

    fmt = AvroInputDataFormat(add_intercept=True)
    fast = fmt.load([path])

    monkeypatch.setattr(native_avro, "available", lambda: False)
    slow = AvroInputDataFormat(add_intercept=True).load([path])

    assert fast.index_map._fwd == slow.index_map._fwd
    for field in ("indices", "values", "labels", "offsets", "weights"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fast.batch, field)),
            np.asarray(getattr(slow.batch, field)),
        )

    # selected-features filter parity
    some = sorted(fast.index_map._fwd)[:10]
    f2 = AvroInputDataFormat(add_intercept=True, selected_features=some)
    fast2 = f2.load([path])
    monkeypatch.undo()
    assert native_avro.available()
    fast2b = AvroInputDataFormat(
        add_intercept=True, selected_features=some
    ).load([path])
    np.testing.assert_array_equal(
        np.asarray(fast2.batch.values), np.asarray(fast2b.batch.values)
    )


def test_game_dataset_parity(tmp_path, rng):
    """build_game_dataset_from_files (native columns) must equal the
    record-at-a-time Python builder on the same files."""
    import os, sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_game_drivers import write_game_avro
    from photon_ml_tpu.game.config import FeatureShardConfiguration
    from photon_ml_tpu.game.data import (
        build_game_dataset,
        build_game_dataset_from_files,
    )
    from photon_ml_tpu.io.avro_codec import read_avro_records

    d = tmp_path / "game"
    d.mkdir()
    write_game_avro(str(d / "p0.avro"), rng, n=150)
    write_game_avro(str(d / "p1.avro"), rng, n=90, seed_shift=1)

    shards = [
        FeatureShardConfiguration("g", ["features"], add_intercept=True),
        FeatureShardConfiguration("u", ["userFeatures"], add_intercept=True),
    ]
    fast = build_game_dataset_from_files([str(d)], shards, ["userId"])
    slow = build_game_dataset(
        read_avro_records([str(d)]), shards, ["userId"]
    )
    assert fast.num_real_rows == slow.num_real_rows == 240
    assert fast.uids == slow.uids
    np.testing.assert_array_equal(fast.labels, slow.labels)
    np.testing.assert_array_equal(fast.offsets, slow.offsets)
    np.testing.assert_array_equal(fast.weights, slow.weights)
    for sid in ("g", "u"):
        np.testing.assert_array_equal(
            fast.shards[sid].indices, slow.shards[sid].indices
        )
        np.testing.assert_array_equal(
            fast.shards[sid].values, slow.shards[sid].values
        )
        assert (
            fast.shards[sid].index_map._fwd == slow.shards[sid].index_map._fwd
        )
    np.testing.assert_array_equal(
        fast.entity_codes["userId"], slow.entity_codes["userId"]
    )
    assert fast.entity_indexes["userId"].ids == slow.entity_indexes["userId"].ids


def test_game_dataset_null_top_level_id_falls_back_to_metadata_map(
    tmp_path, rng
):
    """A nullable top-level entity-id field whose value is (sometimes)
    null must resolve per record from metadataMap, exactly like the
    Python builder's id_of fallback."""
    from photon_ml_tpu.game.config import FeatureShardConfiguration
    from photon_ml_tpu.game.data import (
        build_game_dataset,
        build_game_dataset_from_files,
    )
    from photon_ml_tpu.io.avro_codec import read_avro_records, write_container
    from photon_ml_tpu.io import schemas

    schema = {
        "name": "GameExample2", "type": "record",
        "fields": [
            {"name": "response", "type": "double"},
            {"name": "userId", "type": ["null", "string"], "default": None},
            {
                "name": "metadataMap",
                "type": ["null", {"type": "map", "values": "string"}],
                "default": None,
            },
            {
                "name": "features",
                "type": {"type": "array", "items": schemas.FEATURE_AVRO},
            },
        ],
    }
    recs = []
    for i in range(60):
        u = f"user{i % 5}"
        # odd rows: id in the top-level field; even rows: null there,
        # value only in metadataMap
        recs.append({
            "response": float(i % 2),
            "userId": u if i % 2 else None,
            "metadataMap": None if i % 2 else {"userId": u},
            "features": [
                {"name": "f0", "term": "", "value": float(rng.normal())}
            ],
        })
    d = tmp_path / "game"
    d.mkdir()
    write_container(str(d / "p.avro"), schema, recs)

    shards = [FeatureShardConfiguration("g", ["features"], add_intercept=True)]
    fast = build_game_dataset_from_files([str(d)], shards, ["userId"])
    slow = build_game_dataset(read_avro_records([str(d)]), shards, ["userId"])
    np.testing.assert_array_equal(
        fast.entity_codes["userId"], slow.entity_codes["userId"]
    )
    assert fast.entity_indexes["userId"].ids == slow.entity_indexes["userId"].ids
