"""DateRange parsing + dated input-path expansion (DateRange.scala /
IOUtils.getInputPathsWithinDateRange analogs) and the per-iteration model
tracker that backs validate-per-iteration."""

import datetime
import os

import numpy as np
import pytest

from photon_ml_tpu.utils.date_range import (
    DateRange,
    daily_path,
    input_paths_within_date_range,
    resolve_date_range,
)


class TestDateRange:
    def test_from_dates(self):
        r = DateRange.from_dates("20160101-20160103")
        assert r.start == datetime.date(2016, 1, 1)
        assert r.end == datetime.date(2016, 1, 3)
        assert [d.day for d in r.days()] == [1, 2, 3]

    def test_start_after_end_rejected(self):
        with pytest.raises(ValueError, match="comes after"):
            DateRange.from_dates("20160105-20160101")

    def test_bad_format_rejected(self):
        with pytest.raises(ValueError, match="Couldn't parse"):
            DateRange.from_dates("2016/01/01-20160103")
        with pytest.raises(ValueError, match="separate two values"):
            DateRange.from_dates("20160101")

    def test_from_days_ago(self):
        now = datetime.date(2016, 3, 10)
        r = DateRange.from_days_ago("9-1", now=now)
        assert r.start == datetime.date(2016, 3, 1)
        assert r.end == datetime.date(2016, 3, 9)

    def test_days_ago_validation(self):
        with pytest.raises(ValueError, match="valid integers"):
            DateRange.from_days_ago("a-1")

    def test_resolve_both_given_rejected(self):
        with pytest.raises(ValueError, match="at most one"):
            resolve_date_range("20160101-20160102", "9-1")
        assert resolve_date_range(None, None) is None


class TestInputPathExpansion:
    @pytest.fixture
    def daily_tree(self, tmp_path):
        base = tmp_path / "input"
        days = [datetime.date(2016, 1, d) for d in (1, 2, 4)]  # 3rd missing
        for day in days:
            p = daily_path(str(base), day)
            os.makedirs(p)
            open(os.path.join(p, "part-0.avro"), "w").close()
        return str(base)

    def test_expansion_skips_missing(self, daily_tree):
        r = DateRange.from_dates("20160101-20160104")
        paths = input_paths_within_date_range(daily_tree, r)
        assert len(paths) == 3
        assert paths[0].endswith(os.path.join("daily", "2016", "01", "01"))
        assert paths[-1].endswith(os.path.join("daily", "2016", "01", "04"))

    def test_error_on_missing(self, daily_tree):
        r = DateRange.from_dates("20160101-20160104")
        with pytest.raises(FileNotFoundError, match="does not exist"):
            input_paths_within_date_range(daily_tree, r, error_on_missing=True)

    def test_no_data_in_range_errors(self, daily_tree):
        r = DateRange.from_dates("20170101-20170102")
        with pytest.raises(FileNotFoundError, match="No data folder found"):
            input_paths_within_date_range(daily_tree, r)

    def test_multiple_base_dirs(self, daily_tree, tmp_path):
        base2 = tmp_path / "input2"
        p = daily_path(str(base2), datetime.date(2016, 1, 2))
        os.makedirs(p)
        r = DateRange.from_dates("20160101-20160104")
        paths = input_paths_within_date_range([daily_tree, str(base2)], r)
        assert len(paths) == 4


class TestCoefficientTracking:
    def test_lbfgs_tracks_models(self, rng):
        import jax.numpy as jnp

        from photon_ml_tpu.optim import minimize_lbfgs

        d = 6
        A = rng.normal(size=(16, d)).astype(np.float32)
        b = rng.normal(size=16).astype(np.float32)

        def vg(w):
            r = A @ w - b
            return 0.5 * jnp.vdot(r, r), A.T @ r

        res = minimize_lbfgs(
            vg, jnp.zeros(d), max_iter=30, track_coefficients=True
        )
        coefs = np.asarray(res.tracker.coefs)
        count = int(res.tracker.count)
        assert coefs.shape[1] == d
        # slot 0 is the initial point, last filled slot the final iterate
        np.testing.assert_array_equal(coefs[0], 0.0)
        np.testing.assert_allclose(
            coefs[count - 1], np.asarray(res.coefficients), atol=1e-6
        )
        # default keeps the trace coefficient-free
        res2 = minimize_lbfgs(vg, jnp.zeros(d), max_iter=5)
        assert res2.tracker.coefs is None
