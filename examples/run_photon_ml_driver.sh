#!/bin/bash
# Demonstrates a full GLM driver invocation from the command line, the
# analog of the reference's examples/run_photon_ml_driver.sh (which wraps
# spark-submit; here the "cluster" is the attached TPU and the working
# root is a local/posix path instead of HDFS).
#
# Assumed working-root layout (same as the reference script):
#   train dataset input:  <working_root>/input/train     (Avro or LibSVM)
#   test dataset input:   <working_root>/input/test
# Outputs:
#   models + metrics:     <working_root>/results
#   feature summary:      <working_root>/summary
#
# Example end-to-end with the a1a tutorial dataset:
#   python dev-scripts/libsvm_text_to_trainingexample_avro.py a1a.txt \
#       work/input/train/a1a.avro
#   python dev-scripts/libsvm_text_to_trainingexample_avro.py a1a.t.txt \
#       work/input/test/a1a.avro
#   examples/run_photon_ml_driver.sh work

set -euo pipefail

JOB_NAME="run-photon-ml-driver"
TASK="LOGISTIC_REGRESSION"
REG_WEIGHTS="0.1,1,10,100"
MAX_ITER=50

usage() {
  echo "Usage: $0 [options...] working_root" >&2
  echo >&2
  echo "Options:" >&2
  echo "  -h, --help          Show this message" >&2
  echo "  -n, --job-name S    Job name (default: $JOB_NAME)" >&2
  echo "  -t, --task S        Task type (default: $TASK)" >&2
  echo "  -l, --lambdas S     Comma-separated reg weights (default: $REG_WEIGHTS)" >&2
  echo "  -i, --max-iter N    Max optimizer iterations (default: $MAX_ITER)" >&2
  exit "${1:-2}"
}

while [[ $# -gt 0 ]]; do
  case "$1" in
    -h|--help) usage 0 ;;
    -n|--job-name) JOB_NAME="$2"; shift 2 ;;
    -t|--task) TASK="$2"; shift 2 ;;
    -l|--lambdas) REG_WEIGHTS="$2"; shift 2 ;;
    -i|--max-iter) MAX_ITER="$2"; shift 2 ;;
    -*) echo "unknown option: $1" >&2; usage ;;
    *) break ;;
  esac
done
[[ $# -eq 1 ]] || usage
# absolute path: the script cds to the repo root below, which would
# silently re-anchor a relative working root
ROOT="$(cd "$1" 2>/dev/null && pwd)" || {
  echo "missing working root: $1" >&2; exit 1; }

[[ -d "$ROOT/input/train" ]] || {
  echo "missing train input dir: $ROOT/input/train" >&2; exit 1; }

VALIDATE_ARGS=()
if [[ -d "$ROOT/input/test" ]]; then
  VALIDATE_ARGS=(--validating-data-directory "$ROOT/input/test")
fi

cd "$(dirname "$0")/.."

exec python -m photon_ml_tpu.cli.glm_driver \
  --job-name "$JOB_NAME" \
  --training-data-directory "$ROOT/input/train" \
  "${VALIDATE_ARGS[@]}" \
  --output-directory "$ROOT/results" \
  --task "$TASK" \
  --regularization-type L2 \
  --regularization-weights "$REG_WEIGHTS" \
  --num-iterations "$MAX_ITER" \
  --summarization-output-dir "$ROOT/summary" \
  --delete-output-dirs-if-exist true
